"""Graceful shutdown: SIGTERM/SIGINT during a sweep or bench run finalizes
telemetry and removes partially-written files before exit.

On a preempted TPU pod the runtime sends SIGTERM and gives the process a
grace window. Without a handler, an interrupted sweep leaves an obs
manifest stuck in status ``"running"`` (indistinguishable from a crash)
and possibly a partially-written tile temp file. Inside a
:func:`graceful_shutdown` block:

- SIGTERM / SIGINT raise :class:`Interrupted` at the next bytecode, which
  unwinds the sweep loop (atomic-save temp files are cleaned by their own
  ``except BaseException`` paths on the way out);
- every active obs run is finalized with manifest status
  ``"interrupted"`` — a parseable artifact that says "preempted", not
  "crashed";
- any temp file still registered via :func:`track_tmp` (a save that never
  reached its cleanup) is removed;
- any held coordination file registered via :func:`release_on_exit` —
  tile lease files, the elastic scheduler's heartbeat — is released, so
  peers reclaim the preempted host's work at their next poll instead of
  waiting out the lease/heartbeat TTL;
- the process exits via ``SystemExit(128+signum)`` for SIGTERM, or
  re-raises ``KeyboardInterrupt`` for SIGINT (the Python convention).

Handler hygiene: handlers install only in the main thread, only over the
*default* dispositions (a host application's custom handlers are
respected), are restored on block exit, and nesting is reentrant (the
outermost block owns the handlers) — so `run_tiled_grid` can install
unconditionally even when called from `run_tiled_grid_multihost` or an
embedding server.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
from typing import Optional


class Interrupted(BaseException):
    """Raised by the signal handler; derives BaseException so ordinary
    ``except Exception`` recovery code (tile retry, telemetry guards)
    cannot swallow a shutdown request."""

    def __init__(self, signum: int) -> None:
        super().__init__(f"interrupted by signal {signum}")
        self.signum = signum


# Temp files currently being written by atomic-save helpers; a shutdown
# sweeps whatever is still registered (see utils.checkpoint._save_atomic).
_TMP_REGISTRY: set = set()
# Coordination files this process HOLDS and must hand back on shutdown:
# tile lease files and the elastic scheduler's heartbeat file. Releasing
# them on SIGTERM/SIGINT lets peers reclaim the work immediately instead
# of waiting out SBR_STEAL_LEASE_TTL_S / SBR_HEARTBEAT_TTL_S.
_RELEASE_REGISTRY: set = set()
_DEPTH = 0  # reentrancy: only the outermost graceful_shutdown owns handlers


@contextlib.contextmanager
def track_tmp(path):
    """Register ``path`` as an in-flight partial write for the duration."""
    _TMP_REGISTRY.add(str(path))
    try:
        yield
    finally:
        _TMP_REGISTRY.discard(str(path))


def release_on_exit(path) -> None:
    """Register a held coordination file (lease / heartbeat) for removal
    when a graceful shutdown unwinds this process — peers then reclaim the
    work at their next poll instead of waiting out the TTL."""
    _RELEASE_REGISTRY.add(str(path))


def unregister_release(path) -> None:
    """The file was handed back normally; shutdown no longer owns it."""
    _RELEASE_REGISTRY.discard(str(path))


def _release_registered() -> list:
    released = []
    for p in sorted(_RELEASE_REGISTRY):
        try:
            os.remove(p)
            released.append(p)
        except OSError:
            pass
    _RELEASE_REGISTRY.clear()
    return released


def _cleanup_tmp() -> list:
    removed = []
    for p in sorted(_TMP_REGISTRY):
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    _TMP_REGISTRY.clear()
    return removed


def _finalize_obs_interrupted() -> None:
    """Finalize every active obs run with status "interrupted" (lazy
    import: shutdown must work in processes that never started telemetry)."""
    try:
        from sbr_tpu.obs import runlog

        runlog.interrupt_all()
    except Exception:
        pass  # a failing finalize must not mask the exit itself


@contextlib.contextmanager
def graceful_shutdown(label: str = "run"):
    """Convert SIGTERM/SIGINT into a clean, telemetry-finalizing exit.

    See the module docstring for semantics. Yields None; safe (a plain
    pass-through) off the main thread and under nested use.
    """
    global _DEPTH
    if threading.current_thread() is not threading.main_thread():
        yield  # handlers are main-thread-only in CPython
        return
    if _DEPTH > 0:  # nested: the outermost block already owns the handlers
        _DEPTH += 1
        try:
            yield
        finally:
            _DEPTH -= 1
        return

    def handler(signum, frame):
        raise Interrupted(signum)

    previous = {}
    for sig, default in (
        (signal.SIGTERM, signal.SIG_DFL),
        (signal.SIGINT, signal.default_int_handler),
    ):
        current = signal.getsignal(sig)
        if current == default:  # respect an embedder's custom handlers
            previous[sig] = current
            signal.signal(sig, handler)

    _DEPTH = 1
    try:
        yield
    except Interrupted as itr:
        _finalize_obs_interrupted()
        _cleanup_tmp()
        _release_registered()
        if itr.signum == signal.SIGINT:
            raise KeyboardInterrupt from itr
        raise SystemExit(128 + itr.signum) from itr
    finally:
        _DEPTH -= 1
        for sig, prev in previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass


def interrupted_status() -> Optional[str]:
    """Hook for tests: the registry size (debug aid)."""
    return (
        f"tracked_tmp={len(_TMP_REGISTRY)} "
        f"held_releases={len(_RELEASE_REGISTRY)} depth={_DEPTH}"
    )
