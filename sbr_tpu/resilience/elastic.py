"""Elastic sweep scheduler: hosts join/leave a live tiled sweep, tile
ownership rebalances by measured throughput, and a cross-run global tile
cache makes repeated/overlapping sweeps incremental (ISSUE 8).

PR 4's lease-based work stealing proved a faulted+resumed sweep stays
byte-identical; this module promotes that substrate into a real elastic
scheduler — the preemptible-TPU-pod model where the roster is never fixed:

**Membership.** Every participating host announces itself with a heartbeat
file (``host_<id>.hb``, JSON ``{host, pid, ts, ttl_s, tiles_done,
cells_per_sec}``) in the shared checkpoint dir, refreshed between tiles —
the same filesystem-rendezvous discipline as the tile files themselves, so
membership needs no coordinator. A host that JOINS a running sweep simply
starts claiming unowned tiles from the remaining queue; a host that LEAVES
gracefully (SIGTERM/SIGINT → `resilience.shutdown`) releases its held
leases and heartbeat so peers reclaim its work at their next poll, and a
host that dies silently ages out via the lease/heartbeat TTLs
(``SBR_STEAL_LEASE_TTL_S`` / ``SBR_HEARTBEAT_TTL_S``).

**Throughput-aware rebalancing.** There is no launch-time modulo split:
each poll, every host derives the SAME deterministic claim plan
(`plan_claims`) — greedy longest-processing-time assignment of the
remaining tiles over the live hosts, weighted by each host's published
cells/sec (measured in-run as an EWMA, seeded from the PR 3 perf history's
``elastic_cells_per_sec`` records) — and tries to lease its own share
first, falling back to any unleased tile so the queue is always
work-conserving. Fast hosts therefore claim proportionally more of the
remaining queue, and the per-tile lease files (atomic ``O_EXCL`` create,
TTL takeover — `parallel.distributed._try_lease`) stay the single
arbiter, so a plan disagreement can only ever cost a benign duplicate
compute, never a wrong grid.

**Cross-run global tile cache.** `TileCache` (root ``SBR_TILE_CACHE_DIR``)
is a content-addressed store keyed by the sha256 of the canonicalized
(params, config, dtype, x64 flag, grid-program version, tile β values,
tile u values) — built on `utils.checkpoint.canonicalize`, the same
machinery as `params_fingerprint` — so a tile computed by ANY sweep is
reusable by every later sweep whose cell numerics match, including
overlapping grids. Entries carry sha256 sidecars (`resilience.heal`) and
are verified on read: a mismatch is quarantined beside the cache and the
tile recomputed, never trusted. Hits refresh the entry mtime, which is
what ``report gc --tile-cache DIR --keep-days N`` uses to prune cold
entries.

Every membership change, claim, completion, and cache outcome is an obs
``scheduler`` / ``cache`` event (``python -m sbr_tpu.obs.report elastic
RUN_DIR`` renders and gates them), and the PR 4 invariant is preserved:
any churn schedule yields a grid byte-identical to the fault-free
single-host run (asserted in CI by ``python -m sbr_tpu.resilience.chaos
--churn``).

Module import stays jax-free (stdlib + numpy): the report CLI imports it
for cache gc, and all sbr_tpu machinery is imported lazily inside the
functions that need a live solver.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import socket
import tempfile
import time
import uuid
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

_FIELDS = ("max_aw", "xi", "status")  # mirrors utils.checkpoint._FIELDS

# Heartbeats refresh at tile boundaries (never mid-compute), so the TTL
# must comfortably exceed the worst-case tile wall-clock or a working host
# reads as dead between beats. 300 s covers paper-resolution tiles with
# margin; size SBR_HEARTBEAT_TTL_S to your tile duration, not your
# failure-detection appetite — the lease TTL protects claimed tiles.
DEFAULT_HEARTBEAT_TTL_S = 300.0


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------


def elastic_enabled(flag: Optional[bool] = None) -> bool:
    """Resolve the elastic opt-out: an explicit ``flag`` wins, else
    ``SBR_ELASTIC`` (default ON — set ``SBR_ELASTIC=0`` for the legacy
    launch-time static split)."""
    if flag is not None:
        return bool(flag)
    return os.environ.get("SBR_ELASTIC", "").strip() != "0"


def heartbeat_ttl_s(value: Optional[float] = None) -> float:
    if value is not None:
        return float(value)
    raw = os.environ.get("SBR_HEARTBEAT_TTL_S", "").strip()
    return float(raw) if raw else DEFAULT_HEARTBEAT_TTL_S


def default_tile_cache(cache_dir=None) -> Optional["TileCache"]:
    """The cross-run cache from ``SBR_TILE_CACHE_DIR`` (None = disabled)."""
    root = cache_dir or os.environ.get("SBR_TILE_CACHE_DIR", "").strip()
    return TileCache(root) if root else None


_HOST_ID: Optional[str] = None


def host_identity() -> str:
    """Stable per-process host id: hostname + pid + a random suffix so two
    workers on one box (or a fast pid reuse) can never share an identity."""
    global _HOST_ID
    if _HOST_ID is None:
        name = re.sub(r"[^A-Za-z0-9_.-]", "-", socket.gethostname())[:48]
        _HOST_ID = f"{name}-p{os.getpid()}-{uuid.uuid4().hex[:6]}"
    return _HOST_ID


# ---------------------------------------------------------------------------
# Telemetry hooks (guarded: telemetry must never sink the scheduler)
# ---------------------------------------------------------------------------


def _log_sched(action: str, **fields) -> None:
    try:
        from sbr_tpu import obs

        obs.log_scheduler(action=action, **fields)
    except Exception:
        pass


def _log_cache(action: str, **fields) -> None:
    try:
        from sbr_tpu import obs

        obs.log_cache(action=action, **fields)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Membership: heartbeat files beside the tiles
# ---------------------------------------------------------------------------


def heartbeat_path(ckpt_dir, host: str) -> Path:
    return Path(ckpt_dir) / f"host_{host}.hb"


class Heartbeat:
    """One host's liveness record in the checkpoint dir (atomic rewrite,
    TTL like leases). Registered with `resilience.shutdown` so a graceful
    preemption hands the slot back immediately instead of aging out."""

    def __init__(self, ckpt_dir, host: Optional[str] = None, ttl_s: Optional[float] = None):
        self.host = host or host_identity()
        self.ttl_s = heartbeat_ttl_s(ttl_s)
        self.path = heartbeat_path(ckpt_dir, self.host)
        self.started_at = time.time()

    def beat(self, **stats) -> None:
        rec = {
            "host": self.host,
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "ts": time.time(),
            "ttl_s": self.ttl_s,
            "started_at": self.started_at,
            **stats,
        }
        # A beat is pure liveness telemetry: a transient hiccup on the
        # shared volume (EIO/ESTALE/ENOSPC) must not sink the sweep host —
        # the next beat retries, and worst case peers briefly replan around
        # us (benign: leases still protect claimed tiles).
        try:
            tmp = Path(f"{self.path}.{os.getpid()}.tmp")
            tmp.write_text(json.dumps(rec))
            os.replace(tmp, self.path)
        except OSError:
            return
        try:
            from sbr_tpu.resilience import shutdown

            shutdown.release_on_exit(self.path)
        except Exception:
            pass

    def withdraw(self) -> None:
        try:
            from sbr_tpu.resilience import shutdown

            shutdown.unregister_release(self.path)
        except Exception:
            pass
        try:
            self.path.unlink()
        except OSError:
            pass


def live_hosts(ckpt_dir, now: Optional[float] = None) -> Dict[str, dict]:
    """Parse every heartbeat in the dir; returns {host_id: record} for
    hosts whose TTL has not lapsed. Unreadable heartbeats (torn write from
    a dying host) count as dead."""
    now = time.time() if now is None else now
    out: Dict[str, dict] = {}
    for hb in sorted(Path(ckpt_dir).glob("host_*.hb")):
        try:
            rec = json.loads(hb.read_text())
            ts = float(rec.get("ts", 0.0))
            ttl = float(rec.get("ttl_s", DEFAULT_HEARTBEAT_TTL_S))
        except (OSError, ValueError):
            continue
        if now - ts < ttl:
            out[str(rec.get("host", hb.stem[len("host_"):]))] = rec
    return out


# ---------------------------------------------------------------------------
# Deterministic cost model + rebalancing plan
# ---------------------------------------------------------------------------


def recorded_tile_shape(checkpoint_dir) -> Optional[Tuple[int, int]]:
    """The RESOLVED tile shape the sweep's creating host recorded in the
    checkpoint manifest (`utils.checkpoint._check_fingerprint`) — what a
    late joiner with ``tile_shape="auto"`` must adopt: re-planning from its
    OWN device capacity would fingerprint-mismatch on a heterogeneous
    fleet instead of joining. None for a fresh dir or a pre-ISSUE-8
    manifest (the joiner then resolves locally, the historical behavior)."""
    try:
        doc = json.loads((Path(checkpoint_dir) / "manifest.json").read_text())
        shape = doc.get("tile_shape")
        if isinstance(shape, list) and len(shape) == 2:
            return int(shape[0]), int(shape[1])
    except (OSError, ValueError):
        pass
    return None


def tile_cells(origin: Tuple[int, int], nb: int, nu: int, tile_shape: Tuple[int, int]) -> int:
    bi, ui = origin
    tb, tu = tile_shape
    return max(0, min(tb, nb - bi)) * max(0, min(tu, nu - ui))


def plan_claims(
    tiles: List[Tuple[Tuple[int, int], float]],
    rates: Dict[str, float],
) -> Dict[str, List[Tuple[int, int]]]:
    """Deterministic throughput-weighted LPT assignment of the remaining
    tile queue over the live hosts.

    ``tiles`` is ``[(origin, cost), ...]`` (cost in cells, or seconds —
    any consistent unit); ``rates`` maps host id → published throughput
    (cells/sec; non-positive/missing treated as 1.0). Tiles are placed
    largest-cost-first onto the host with the smallest projected finish
    time ``(load + cost) / rate`` (ties broken by host id, then plan
    order), so every host computes the IDENTICAL plan from the same
    heartbeat snapshot — coordination-free rebalancing, with the per-tile
    leases as the actual arbiter when snapshots momentarily differ.
    """
    hosts = sorted(rates)
    plan: Dict[str, List[Tuple[int, int]]] = {h: [] for h in hosts}
    if not hosts:
        return plan
    eff = {h: (float(rates[h]) if float(rates.get(h) or 0.0) > 0 else 1.0) for h in hosts}
    loads = {h: 0.0 for h in hosts}
    # Largest cost first (LPT); origin tie-break keeps the order total.
    for origin, cost in sorted(tiles, key=lambda tc: (-tc[1], tc[0])):
        best = min(hosts, key=lambda h: ((loads[h] + cost) / eff[h], h))
        plan[best].append(origin)
        loads[best] += float(cost)
    return plan


class ThroughputTracker:
    """EWMA cells/sec for THIS host, seeded from the perf history so a
    rejoining host starts from its fleet-typical rate instead of 1.0."""

    def __init__(self, seed_rate: Optional[float] = None, alpha: float = 0.5):
        self.rate = seed_rate
        self.alpha = alpha

    def update(self, cells: int, dur_s: float) -> None:
        if dur_s <= 0 or cells <= 0:
            return
        r = cells / dur_s
        self.rate = r if self.rate is None else self.alpha * r + (1 - self.alpha) * self.rate


def _platform() -> Optional[str]:
    """Backend platform, best-effort (the sweep just ran, so a backend is
    already live; never the reason jax initializes)."""
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return None


def _rate_history_path():
    """SIDECAR history for elastic throughput records
    (``<SBR_OBS_HISTORY>.elastic.jsonl``): `report trend --check` gates
    only the LATEST record of the main history, so an elastic_sweep line
    landing after a bench line would mask (or short-circuit) the bench
    gate — the cost-model records therefore live beside, not inside, the
    gated file."""
    from sbr_tpu.obs import history
    from pathlib import Path as _P

    return _P(str(history.history_path()) + ".elastic.jsonl")


def seed_rate_from_history(path=None, window: int = 8) -> Optional[float]:
    """Median of this platform's most recent ``elastic_cells_per_sec``
    records in the elastic sidecar history — the deterministic cost-model
    seed (CPU smoke rates must not seed a TPU host, hence the platform
    filter). None when no such metric was ever recorded."""
    try:
        from sbr_tpu.obs import history

        return history.recent_median(
            "elastic_cells_per_sec",
            path=path or _rate_history_path(),
            platform=_platform(),
            window=window,
        )
    except Exception:
        return None


def _append_rate_history(rate: Optional[float], tiles_computed: int) -> None:
    """Record this sweep's MEASURED throughput for future cost-model seeds
    (an all-cache-hit sweep measured nothing and must not echo its seed
    back). Gated on an explicit SBR_OBS_HISTORY (like bench tiny runs):
    tests and ad-hoc sweeps must not grow a committed history."""
    if not rate or tiles_computed <= 0 or not os.environ.get("SBR_OBS_HISTORY", "").strip():
        return
    try:
        from sbr_tpu.obs import history

        history.append(
            {"elastic_cells_per_sec": float(rate)},
            label="elastic_sweep",
            platform=_platform(),
            path=_rate_history_path(),
            meta={"tiles": tiles_computed, "host": host_identity()},
        )
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Cross-run global tile cache
# ---------------------------------------------------------------------------


def cell_tag(params, config, dtype_name: str) -> str:
    """Canonical tag of everything that — together with (β, u) — determines
    one sweep cell's bytes: the non-swept economics/learning scalars, the
    solver config, the resolved dtype, the x64 flag, the grid-program
    version, and the params type name. The serving fleet's degradation
    ladder (`sbr_tpu.serve.fleet.TileCacheBridge`) matches a point query
    to a swept tile exactly when their tags agree — this ONE function is
    both sides of that contract, so they cannot drift."""
    from sbr_tpu.utils.checkpoint import canonicalize

    x64 = None
    try:
        import jax

        x64 = bool(jax.config.jax_enable_x64)
    except Exception:
        pass
    version = 0
    try:
        from sbr_tpu.sweeps.baseline_sweeps import GRID_PROGRAM_VERSION

        version = int(GRID_PROGRAM_VERSION)
    except Exception:
        pass
    e, l = params.economic, params.learning
    return canonicalize(
        (
            type(params).__name__,
            float(e.p), float(e.kappa), float(e.lam), float(e.eta),
            float(l.tspan[0]), float(l.tspan[1]), float(l.x0),
            config, str(dtype_name), x64, version,
        )
    )


def tile_meta(base, config, dtype, tile_betas, tile_us, key: str) -> dict:
    """The ``<key>.meta.json`` document a tile store leaves beside its
    entry: the cell tag plus the tile's actual β/u axes — what turns a
    content-addressed whole tile into per-cell addressable answers for
    the serving fleet's degradation ladder. ``dtype`` is resolved to the
    concrete default exactly as the sweep entry points resolve it, so a
    ``dtype=None`` sweep and a serve engine that resolved f64 agree."""
    dtype_name = str(dtype)
    try:
        import jax
        import jax.numpy as jnp

        if dtype is None:
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        dtype_name = jax.dtypes.canonicalize_dtype(np.dtype(dtype)).name
    except Exception:
        if dtype is None:
            dtype_name = "None"
    return {
        "key": key,
        "cell_tag": cell_tag(base, config, dtype_name),
        "betas": [float(b) for b in np.asarray(tile_betas).ravel()],
        "us": [float(u) for u in np.asarray(tile_us).ravel()],
    }


class TileCache:
    """Content-addressed cross-run tile store (see module docstring).

    Layout: ``<root>/<key[:2]>/<key>.npz`` + ``.sha256`` sidecar (and,
    when the store supplies one, a ``<key>.meta.json`` cell-index sidecar
    for the serving fleet's degradation ladder); writes
    are atomic (tmp + rename, losing a race to a peer writing the SAME key
    is fine — identical content by construction); reads verify the sidecar
    and QUARANTINE mismatches (``<root>/<key[:2]>/quarantine/``) rather
    than trusting or deleting them. Hits `os.utime`-refresh the entry so
    cold-entry gc (`gc_tile_cache`) never evicts a warm region."""

    def __init__(self, root):
        self.root = Path(root)

    def key(self, base, config, dtype, tile_betas, tile_us) -> str:
        """sha256 over everything that determines the tile's bytes: the
        canonicalized params/config (the `params_fingerprint` machinery),
        dtype, the x64 flag (a dtype=None sweep canonicalizes differently
        under it), the grid program version (bumped when cell numerics
        change — `sweeps.baseline_sweeps.GRID_PROGRAM_VERSION`), and the
        tile's ACTUAL β/u values — so overlapping grids share entries
        exactly when their cells are mathematically identical."""
        from sbr_tpu.utils.checkpoint import canonicalize

        x64 = None
        try:
            import jax

            x64 = bool(jax.config.jax_enable_x64)
        except Exception:
            pass
        version = 0
        try:
            from sbr_tpu.sweeps.baseline_sweeps import GRID_PROGRAM_VERSION

            version = int(GRID_PROGRAM_VERSION)
        except Exception:
            pass
        payload = canonicalize(
            (
                base,
                config,
                str(dtype),
                x64,
                version,
                np.ascontiguousarray(np.asarray(tile_betas, dtype=np.float64)),
                np.ascontiguousarray(np.asarray(tile_us, dtype=np.float64)),
            )
        )
        return hashlib.sha256(payload.encode()).hexdigest()

    def path(self, key: str) -> Path:
        return self.root / key[:2] / f"{key}.npz"

    def load(self, key: str, tile: str = "?") -> Optional[dict]:
        """Verified read; None on miss or corruption (corrupt entries are
        quarantined + logged, and the caller recomputes)."""
        path = self.path(key)
        if not path.exists():
            _log_cache("miss", tile=tile, key=key[:12])
            return None
        from sbr_tpu.resilience import faults, heal
        from sbr_tpu.resilience.faults import InjectedFault

        # The fault point fires OUTSIDE the quarantine handler: an injected
        # transient read failure means "fall back to compute" (a miss),
        # never "destroy a healthy entry".
        try:
            faults.fire("tilecache.load", target=tile)
        except InjectedFault:
            _log_cache("miss", tile=tile, key=key[:12], injected=True)
            return None
        try:
            # Unlike local checkpoints, the cache has NO legitimate
            # pre-sidecar "legacy" entries: a sidecar-less entry means
            # `store` died between the rename and the sidecar write, and
            # later rot in it would be unverifiable — quarantine anything
            # that is not a verified "ok", never trust it.
            if heal.verify_file(path) != "ok":
                heal.quarantine(path, reason="tilecache-unverifiable")
                _log_cache("quarantine", tile=tile, key=key[:12])
                return None
            data = np.load(path)
            arrays = {f: data[f] for f in _FIELDS}
        except Exception as err:
            if path.exists():
                heal.quarantine(path, reason=f"tilecache-unreadable: {err!r}")
            _log_cache("quarantine", tile=tile, key=key[:12])
            return None
        try:  # a hit is a "use": keep the entry warm for keep-days gc
            os.utime(path)
        except OSError:
            pass
        _log_cache("hit", tile=tile, key=key[:12])
        return arrays

    def store(self, key: str, arrays: dict, tile: str = "?",
              meta: Optional[dict] = None) -> Optional[Path]:
        from sbr_tpu.resilience import heal, shutdown

        path = self.path(key)
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
            try:
                # track_tmp: a graceful shutdown sweeps the partial write
                # even if this frame's cleanup never runs; a hard kill
                # leaves it for gc_tile_cache's *.tmp debris sweep.
                with shutdown.track_tmp(tmp):
                    with os.fdopen(fd, "wb") as fh:
                        np.savez(fh, **{f: np.asarray(arrays[f]) for f in _FIELDS})
                    # Sidecar BEFORE the rename (hashed from the staged
                    # tmp): a concurrent reader sees either nothing or a
                    # fully verifiable entry — never the rename-then-
                    # sidecar window that load() would have to quarantine.
                    # A crash here leaves an orphan sidecar, swept by
                    # gc_tile_cache; a racer writing the same key writes
                    # identical bytes (deterministic), so overwrites agree.
                    heal.write_sidecar(path, source=tmp)
                    os.replace(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.remove(tmp)
                raise
        except OSError:
            return None  # a read-only/full cache volume must not sink the sweep
        if meta is not None:
            # Cell-index sidecar (ISSUE 11): best-effort and AFTER the entry
            # rename — a missing/torn meta file only makes the entry
            # invisible to the serving bridge, never wrong (the bridge
            # re-verifies the entry itself through `load`). Atomic like
            # everything else beside it.
            try:
                meta_path = Path(str(path)[: -len(".npz")] + ".meta.json")
                fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
                with os.fdopen(fd, "w") as fh:
                    fh.write(json.dumps(meta))
                os.replace(tmp, meta_path)
            except OSError:
                pass
        _log_cache("store", tile=tile, key=key[:12])
        return path


def gc_tile_cache(root, keep_days: float = 30.0, now: Optional[float] = None) -> list:
    """Prune COLD global-cache entries: any ``.npz`` (plus its sidecar)
    not read or written for ``keep_days`` (hits refresh mtime). Entries
    under a ``quarantine/`` dir are evidence and are removed too (an
    explicit gc is entitled to clear evidence, matching `mem.gc_debris`).
    Orphaned ``*.tmp`` store files (a writer hard-killed between mkstemp
    and rename) older than an hour are always debris. Never touches other
    files; returns the removed paths."""
    import shutil

    root = Path(root)
    removed: list = []
    if not root.is_dir():
        return removed
    now = time.time() if now is None else now
    horizon = now - keep_days * 86400.0
    # Quarantine dirs first, unconditionally (matching mem.gc_debris):
    # quarantined entries keep a fresh mtime from their os.replace, so the
    # keep-days horizon below would wrongly preserve the evidence.
    for q in sorted(root.rglob("quarantine")):
        if not q.is_dir():
            continue
        try:
            shutil.rmtree(q)
            removed.append(q)
        except OSError:
            pass
    for entry in sorted(root.rglob("*.npz")):
        try:
            if entry.stat().st_mtime > horizon:
                continue
            entry.unlink()
            removed.append(entry)
        except OSError:
            continue
        for side in (
            Path(str(entry) + ".sha256"),
            Path(str(entry)[: -len(".npz")] + ".meta.json"),
        ):
            try:
                side.unlink()
                removed.append(side)
            except OSError:
                pass
    for tmp in sorted(root.rglob("*.tmp")):
        try:
            # An hour of grace covers any live writer (stores take <1 s);
            # anything older is a dead writer's orphan.
            if now - tmp.stat().st_mtime >= 3600.0:
                tmp.unlink()
                removed.append(tmp)
        except OSError:
            continue
    # Orphan sidecars (a writer died between publishing the sidecar and
    # renaming the entry): same hour of grace.
    for side in sorted(root.rglob("*.npz.sha256")):
        try:
            if (
                not Path(str(side)[: -len(".sha256")]).exists()
                and now - side.stat().st_mtime >= 3600.0
            ):
                side.unlink()
                removed.append(side)
        except OSError:
            continue
    # Orphan cell-index metas (entry pruned by an older gc, or a writer
    # died between the entry rename and the meta write's replace).
    for meta in sorted(root.rglob("*.meta.json")):
        try:
            if (
                not Path(str(meta)[: -len(".meta.json")] + ".npz").exists()
                and now - meta.stat().st_mtime >= 3600.0
            ):
                meta.unlink()
                removed.append(meta)
        except OSError:
            continue
    return removed


# ---------------------------------------------------------------------------
# The elastic sweep driver
# ---------------------------------------------------------------------------


def run_elastic_grid(
    beta_values,
    u_values,
    base,
    checkpoint_dir,
    config=None,
    tile_shape=(256, 256),
    dtype=None,
    wait: bool = True,
    poll_s: float = 5.0,
    timeout_s: float = 24 * 3600.0,
    verbose: bool = False,
    lease_ttl_s: Optional[float] = None,
    heartbeat_ttl_s: Optional[float] = None,
    tile_cache_dir=None,
    max_retries: int = 2,
    scenario_spec=None,
):
    """Elastic β×u sweep over a shared checkpoint dir (the scheduler behind
    `parallel.run_tiled_grid_multihost` when elastic mode is on).

    Any number of hosts may run this concurrently against one
    ``checkpoint_dir`` — including hosts started long after the sweep began
    (they announce a heartbeat and start claiming) and hosts that vanish
    mid-run (their leases/heartbeats expire, or are released immediately on
    a graceful SIGTERM, and peers reclaim the tiles). Tile ownership is
    decided per-claim by `plan_claims` + the lease files; the final grid is
    byte-identical to a single-host `run_tiled_grid` of the same sweep
    regardless of the churn schedule.

    ``wait=False`` returns None as soon as nothing is claimable (every
    tile done or leased to a live holder) — the worker-process pattern.
    ``wait=True`` polls until all tiles exist, then assembles the full
    grid from disk (pure read).
    """
    from sbr_tpu.parallel.distributed import _cleanup_leases, _try_lease
    from sbr_tpu.resilience import faults, shutdown
    from sbr_tpu.utils import checkpoint as ckpt_mod

    if checkpoint_dir is None:
        raise ValueError("elastic sweeps need a shared checkpoint_dir (the rendezvous)")
    if lease_ttl_s is None:
        lease_ttl_s = float(os.environ.get("SBR_STEAL_LEASE_TTL_S", "900"))
    if tile_shape == "auto":
        # Late-join on a heterogeneous fleet: adopt the sweep's recorded
        # geometry instead of re-planning from this host's capacity (see
        # `recorded_tile_shape`). First host in: resolves locally and its
        # shape becomes the record.
        adopted = recorded_tile_shape(checkpoint_dir)
        if adopted is not None:
            tile_shape = adopted

    cache = default_tile_cache(tile_cache_dir)
    runner = ckpt_mod.tile_runner(
        beta_values, u_values, base, checkpoint_dir, config=config,
        tile_shape=tile_shape, dtype=dtype, max_retries=max_retries,
        tile_cache=cache, verbose=verbose, scenario_spec=scenario_spec,
    )
    ckpt = runner.ckpt
    tiles = ckpt_mod.tile_origins(runner.nb, runner.nu, (runner.tb, runner.tu))
    costs = {
        t: float(tile_cells(t, runner.nb, runner.nu, (runner.tb, runner.tu)))
        for t in tiles
    }

    hid = host_identity()
    hb = Heartbeat(ckpt, hid, ttl_s=heartbeat_ttl_s)
    tracker = ThroughputTracker(seed_rate=seed_rate_from_history())
    hb.beat(tiles_done=0, cells_per_sec=tracker.rate)
    _log_sched("join", host=hid, tiles=len(tiles), seed_rate=tracker.rate)

    done = 0
    deadline = time.monotonic() + timeout_s
    last_plan_sig = None
    # Incremental remaining-set bookkeeping: ONE full disk scan at join,
    # then tiles leave the set as we produce them or observe them landed
    # (the single pre-claim stat below). A full re-scan happens only when
    # nothing was claimable (the poll path) — so the claim loop costs
    # O(1) stats per claimed tile, not O(n_tiles) per iteration, which
    # matters on the shared network storage every host depends on.
    remaining = {t for t in tiles if not runner.path(*t).exists()}
    # Re-planning is amortized: heartbeats are re-read and the LPT plan
    # recomputed only every REPLAN_EVERY claims (or when the cached claim
    # order drains / nothing was claimable) — a per-claim re-plan would be
    # O(tiles² · hosts) scheduling work plus a heartbeat read per host per
    # tile against the shared storage. Staleness is safe: leases arbitrate
    # every claim, and produce() rechecks the local slot.
    REPLAN_EVERY = 16
    order: list = []
    next_in_order = 0
    claims_since_plan = 0
    # The leave/withdraw finally sits INSIDE the shutdown envelope: on a
    # SIGTERM it runs while unwinding toward graceful_shutdown's handler,
    # i.e. BEFORE the obs run is finalized — so a preempted host's "leave"
    # event still lands in the log and the census shows it departed.
    with shutdown.graceful_shutdown(label="elastic_grid"):
        try:
            while remaining:
                faults.fire("barrier.poll", target=f"missing={len(remaining)}")
                _fl = ckpt_mod._flight_recorder()
                if _fl is not None:
                    _fl.point("collectives", "barrier_poll",
                              tag=f"missing={len(remaining)}")
                if next_in_order >= len(order) or claims_since_plan >= REPLAN_EVERY:
                    hosts = live_hosts(ckpt)
                    rates = {
                        h: float(rec.get("cells_per_sec") or 0.0) or 1.0
                        for h, rec in hosts.items()
                    }
                    rates[hid] = float(tracker.rate or 0.0) or rates.get(hid, 1.0)
                    missing = sorted(remaining)
                    plan = plan_claims([(t, costs[t]) for t in missing], rates)
                    plan_sig = json.dumps({h: len(v) for h, v in sorted(plan.items())})
                    if plan_sig != last_plan_sig:
                        last_plan_sig = plan_sig
                        _log_sched(
                            "plan", host=hid, missing=len(missing),
                            shares={h: len(v) for h, v in sorted(plan.items())},
                        )
                    mine = plan.get(hid, [])
                    mine_set = set(mine)
                    order = mine + [t for t in missing if t not in mine_set]
                    next_in_order = 0
                    claims_since_plan = 0

                claimed = None
                while next_in_order < len(order):
                    bi, ui = order[next_in_order]
                    next_in_order += 1
                    if (bi, ui) not in remaining:
                        continue
                    if runner.path(bi, ui).exists():
                        remaining.discard((bi, ui))  # a peer landed it
                        continue
                    lease = ckpt / f"tile_b{bi:05d}_u{ui:05d}.lease"
                    takeover = lease.exists()
                    if _try_lease(ckpt, bi, ui, lease_ttl_s):
                        claimed = (bi, ui, lease, takeover)
                        break
                    # Leased to a live holder: revisit it on the NEXT plan,
                    # not in this pass — it is being worked on.
                if claimed is None:
                    # Nothing claimable right now: re-scan what is truly
                    # still missing (peers may have landed tiles since the
                    # join-time scan), then exit (worker mode) or poll.
                    remaining = {
                        t for t in remaining if not runner.path(*t).exists()
                    }
                    if not remaining or not wait:
                        break
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"{len(remaining)} tiles still missing after "
                            f"{timeout_s:.0f}s with nothing claimable — live "
                            f"holders: {sorted(live_hosts(ckpt))}; first "
                            f"missing: {sorted(remaining)[0]}"
                        )
                    hb.beat(tiles_done=done, cells_per_sec=tracker.rate)
                    if verbose:
                        print(f"  elastic: waiting on {len(remaining)} leased tile(s) …")
                    time.sleep(poll_s)
                    continue

                bi, ui, lease, takeover = claimed
                tile_id = runner.tile_id(bi, ui)
                _log_sched(
                    "reclaim" if takeover else "claim", host=hid, tile=tile_id,
                )
                shutdown.release_on_exit(lease)
                # Beat at tile START so the staleness clock spans exactly one
                # tile compute — peers (and gc) consider us dead only after
                # TTL of silence measured from here. The TTL must exceed the
                # worst-case tile wall-clock; a host misjudged as dead loses
                # nothing (its leased tile is still protected by the lease
                # TTL, and the plan merely re-shuffles unclaimed tiles).
                hb.beat(tiles_done=done, cells_per_sec=tracker.rate)
                t_tile = time.monotonic()
                try:
                    source, _arrays = runner.produce(bi, ui)
                finally:
                    try:
                        lease.unlink()
                    except OSError:
                        pass
                    shutdown.unregister_release(lease)
                dur = time.monotonic() - t_tile
                if source == "computed":
                    tracker.update(int(costs[(bi, ui)]), dur)
                done += 1
                claims_since_plan += 1
                remaining.discard((bi, ui))
                hb.beat(tiles_done=done, cells_per_sec=tracker.rate)
                _log_sched(
                    "done", host=hid, tile=tile_id, source=source,
                    dur_s=round(dur, 6), cells=int(costs[(bi, ui)]),
                )
                if verbose:
                    print(f"  elastic: {tile_id} {source} in {dur:.3f}s "
                          f"({len(remaining)} left)")
        finally:
            hb.withdraw()
            _log_sched("leave", host=hid, tiles_done=done)

    if runner.ckpt is not None and runner.repairs:
        ckpt_mod._record_repairs(runner.ckpt, runner.repairs)
    # Gate on COMPUTED tiles, not done tiles: an all-cache-hit sweep never
    # measured anything, and re-appending the history-seeded rate would
    # pin recent_median to a stale value forever.
    _append_rate_history(tracker.rate, runner.counts.get("computed", 0))
    if not wait:
        return None

    # Assembly: all tiles on disk — a pure cache read, like the legacy
    # barrier's final pass (the ORIGINAL tile_shape flows down so an
    # "auto" resolution re-runs against its own plan record, free).
    _cleanup_leases(ckpt)
    return ckpt_mod.run_tiled_grid(
        beta_values, u_values, base, config=config, tile_shape=tile_shape,
        checkpoint_dir=checkpoint_dir, dtype=dtype, verbose=verbose,
        tile_cache=cache, scenario_spec=scenario_spec,
    )
