"""Run telemetry subsystem: structured event logs, per-stage tracing with
compile/execute attribution, device metrics, and a metrics registry.

Layers (SURVEY §5.1, §5.5; torchode's solver step statistics and ABMax's
per-step ABM counters are the design references from PAPERS.md):

- ``obs.timing``  — low-level primitives: honest device `fence`,
  `StageTimer`, `jax.profiler` `trace` capture (formerly `utils.timing`).
- ``obs.metrics`` — process-global counters/gauges/timer histograms,
  recorded only at host boundaries (jit-safe); zero overhead disabled.
- ``obs.runlog``  — `RunContext` per-run directories (`events.jsonl` +
  `manifest.json`), `span` stage tracing, `jit_call` AOT compile/execute
  attribution, status-grid accounting, numerical-health censuses
  (`log_health`, fed by `sbr_tpu.diag`), memory snapshots, and run-dir
  retention (`gc_runs`, `SBR_OBS_KEEP`).
- ``obs.prof``    — performance observatory: `jax.monitoring` compile
  listeners (per-span XLA compile attribution), the per-jitted-function
  retrace registry (`note_trace` + ``retrace`` warning events), and
  opt-in profiler capture (`obs.profile`, ``SBR_OBS_PROFILE=1``) with
  `TraceAnnotation`/`StepTraceAnnotation` stage framing.
- ``obs.history`` — append-only perf history (``bench_history.jsonl``):
  every bench run's headline metrics, trend rendering and the regression
  gate (`report trend --check`).
- ``obs.mem``     — memory observatory: per-span/per-tile HBM attribution
  (``mem`` events, ``SBR_OBS_MEM_LIVE`` live-buffer gate, manifest
  ``memory`` roll-up with peak span / top programs by temp size), the
  pre-dispatch OOM preflight (AOT analytical footprint vs
  ``SBR_MEM_HEADROOM × capacity``, fail-closed `MemoryPreflightError`),
  and the ``tile_shape="auto"`` capacity planner.
- ``obs.audit``   — numerics audit observatory (ISSUE 17): the versioned
  golden-surface registry, the unified canary battery
  (``python -m sbr_tpu.obs.audit``; the four legacy parity CLIs delegate
  through it), the serve-worker `AuditScheduler` (``SBR_AUDIT``,
  ``SBR_AUDIT_INTERVAL_S``), and audit-artifact retention
  (`gc_audit_files`, ``report gc --audit-keep``). Kept OUT of this
  package's import graph so ``python -m`` runs exactly one module copy
  (the `graphgen_cli` rationale).
- ``obs.demand``  — workload demand observatory (ISSUE 18): the rolling
  (β, u) demand histogram on the fixed sweep-aligned grid, the mergeable
  Misra-Gries heavy-hitter sketch over query fingerprints, per-bin
  answer-source (warm/cold) labels, the deterministic prefetch advisor
  (``advisor_plan.json``), offline trace replay
  (``python -m sbr_tpu.obs.demand replay``), and demand-artifact
  retention (`gc_demand_files`, ``report gc --demand-keep``). Also kept
  OUT of this package's import graph — and out of the SERVE import graph
  unless ``SBR_DEMAND=1`` (off is a structural no-op: module never
  imported, ``/metrics`` byte-free of ``sbr_demand``).
- ``obs.report``  — `python -m sbr_tpu.obs.report RUN_DIR [OTHER]` renders
  a run directory or diffs two runs; the `health` subcommand renders and
  gates on numerical health, `resilience` renders/gates the fault/retry/
  repair story (`sbr_tpu.resilience`), `trend` renders/gates the perf
  history, `memory` renders/gates per-span/per-tile peak-memory
  attribution, `serve` renders/gates a serving run's rolling live
  telemetry (``live.json`` from `sbr_tpu.serve`; SLO breach = exit 1),
  `elastic` renders the elastic-scheduler census (hosts joined/left,
  claims, tile sources, global-cache outcomes — exit 3 when a churn gate
  has nothing to read), `fleet` renders/gates a serving-fleet router run
  (rolling ``fleet.json`` + fleet events; exit 1 on lost queries or a
  breaker stuck open), `gc` prunes old run directories plus checkpoint
  debris (``quarantine/``, stale ``tile_*.lease``, expired ``host_*.hb``
  heartbeats) and, with ``--tile-cache``, cold cross-run tile-cache
  entries. Every subcommand takes ``--json``. Reports tolerate torn
  ``events.jsonl`` lines (counted and surfaced as ``bad_event_lines``).

Enabling telemetry: set ``SBR_OBS=1`` in the environment (run directories
land under ``SBR_OBS_DIR``, default ``obs_runs/``), or programmatically::

    from sbr_tpu import obs
    with obs.run_context(label="sweep") as run:
        grid = beta_u_grid(...)
    print(run.run_dir)  # manifest.json + events.jsonl

Disabled (the default), every instrumentation site is a single global read
— no events, no fences, no extra device work, and no retraces of library
jit caches (asserted by tests/test_obs.py).
"""

# NOTE: `obs.trace` is the distributed-tracing MODULE (ISSUE 16). The
# profiler-capture context manager formerly re-exported under this name
# lives at its home, `obs.timing.trace` (also `utils.timing.trace`).
from sbr_tpu.obs import history, mem, prof, trace
from sbr_tpu.obs.metrics import MetricsRegistry, metrics
from sbr_tpu.obs.prof import annotate, note_trace, profile, step_annotation
from sbr_tpu.obs.runlog import (
    active_run,
    active_span,
    RunContext,
    current_run,
    enabled,
    end_run,
    event,
    gc_runs,
    interrupt_all,
    jit_call,
    log_audit,
    log_cache,
    log_demand,
    log_fault,
    log_fleet,
    log_health,
    log_infomodel,
    log_prewarm,
    log_repair,
    log_retry,
    log_scheduler,
    log_status,
    log_tile_mem,
    run_context,
    span,
    start_run,
    suspended,
)
from sbr_tpu.obs.timing import StageTimer, fence

__all__ = [
    "MetricsRegistry",
    "RunContext",
    "StageTimer",
    "active_run",
    "active_span",
    "annotate",
    "current_run",
    "enabled",
    "end_run",
    "event",
    "fence",
    "gc_runs",
    "history",
    "interrupt_all",
    "jit_call",
    "log_audit",
    "log_cache",
    "log_demand",
    "log_fault",
    "log_fleet",
    "log_health",
    "log_infomodel",
    "log_prewarm",
    "log_repair",
    "log_retry",
    "log_scheduler",
    "log_status",
    "log_tile_mem",
    "mem",
    "metrics",
    "note_trace",
    "prof",
    "profile",
    "run_context",
    "span",
    "start_run",
    "step_annotation",
    "suspended",
    "trace",
]
