"""Memory observatory: HBM attribution, OOM preflight, and capacity-planned
tiling (ISSUE 5 tentpole).

The sweep grids that reproduce the paper's figures are the memory-bound hot
path of this framework — batched-solver systems like torchode and ABMax
(PAPERS.md) show that footprint, not FLOPs, governs achievable batch width
for vmapped ODE/rootfind stacks. Before this module the telemetry stack
recorded two one-shot allocator snapshots and a tile that OOMed on TPU was
discovered by dying; ``tile_shape=(256, 256)`` was a hard-coded guess.

Three layers, all host-side and zero-overhead when telemetry is off:

- **Attribution** (`snapshot` + runlog wiring): every span end and jit call
  emits a ``mem`` event carrying the live-buffer sum (gated by
  ``SBR_OBS_MEM_LIVE`` — it is O(live arrays) per event), the allocator's
  ``bytes_in_use`` / ``peak_bytes_in_use`` when the backend exposes
  ``memory_stats()`` (TPU/GPU; None on CPU), and deltas vs the previous
  snapshot. The run manifest's ``memory`` block rolls up the peak, the span
  holding it, the top programs by XLA temp size, and per-tile peaks from
  the tiled sweep loop. Render with
  ``python -m sbr_tpu.obs.report memory RUN_DIR [--json]``.
- **OOM preflight** (`aot_footprint` + `preflight`): before a sweep
  dispatches, AOT-lower one tile (`jax.ShapeDtypeStruct` arguments — no
  data, no execution), read the compiled program's analytical footprint
  (argument + output + temp bytes from ``memory_analysis()``), and compare
  it against ``memory_stats()`` capacity scaled by ``SBR_MEM_HEADROOM``
  (default 0.8). Failure is CLOSED — a clear `MemoryPreflightError` before
  any device work, instead of an XLA OOM mid-sweep. On CPU (or any backend
  without ``memory_stats``) the check gracefully skips (verdict
  ``"skipped"``) without paying the AOT compile.
- **Capacity planner** (`plan_tile_shape` / `plan_from_probes`):
  ``tile_shape="auto"`` in the tiled sweeps fits a linear footprint model
  (fixed + per-cell bytes, from two small probe lowerings) and picks the
  largest power-of-two square tile whose modeled footprint fits within
  ``headroom × capacity``. The planner is deterministic: the same capacity
  and model always produce the same shape, so multihost peers planning
  independently agree on the tile grid.

Nothing here imports jax at module scope, and `gc_debris` (the `report gc`
helper that prunes ``quarantine/`` directories and stale ``tile_*.lease``
files) is pure stdlib — so nothing in this module can wake an accelerator
backend. (Note `python -m sbr_tpu.obs.report` still imports the jax module
via the parent package ``__init__`` — as it always has; "accelerator-free"
means no backend is ever initialized, not that jax is absent from
sys.modules.)
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import time
from pathlib import Path
from typing import Callable, Optional, Tuple

# ---------------------------------------------------------------------------
# Env knobs
# ---------------------------------------------------------------------------

DEFAULT_HEADROOM = 0.8


def headroom() -> float:
    """Fraction of device capacity the planner/preflight may budget
    (``SBR_MEM_HEADROOM``, default 0.8 — the rest absorbs allocator
    fragmentation, XLA scratch, and the framework's own persistent buffers)."""
    env = os.environ.get("SBR_MEM_HEADROOM", "").strip()
    try:
        v = float(env) if env else DEFAULT_HEADROOM
    except ValueError:
        return DEFAULT_HEADROOM
    return v if 0.0 < v <= 1.0 else DEFAULT_HEADROOM


def live_enabled() -> bool:
    """Whether snapshots sum `jax.live_arrays()` (``SBR_OBS_MEM_LIVE``,
    default on). The sum is O(live arrays) per event — bench timing loops
    turn it off (`live_disabled`) so instrumentation cannot pad measured
    dispatch times."""
    return os.environ.get("SBR_OBS_MEM_LIVE", "").strip() != "0"


@contextlib.contextmanager
def live_disabled():
    """Temporarily disable the live-buffer sum (measurement-critical
    sections; restores the previous setting on exit)."""
    prev = os.environ.get("SBR_OBS_MEM_LIVE")
    os.environ["SBR_OBS_MEM_LIVE"] = "0"
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("SBR_OBS_MEM_LIVE", None)
        else:
            os.environ["SBR_OBS_MEM_LIVE"] = prev


def preflight_enabled() -> bool:
    """``SBR_MEM_PREFLIGHT`` (default on) gates the pre-dispatch OOM check;
    on capacity-less backends the check is free either way."""
    return os.environ.get("SBR_MEM_PREFLIGHT", "").strip() != "0"


# ---------------------------------------------------------------------------
# Snapshots (attribution layer)
# ---------------------------------------------------------------------------


def live_bytes() -> Optional[int]:
    """Sum of live jax buffer nbytes, or None when gated off / jax absent."""
    if not live_enabled():
        return None
    try:
        import jax

        return int(sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))
    except Exception:
        return None


def allocator_stats() -> Optional[dict]:
    """The default device's ``memory_stats()`` dict, or None (CPU backends
    and some tunneled runtimes return None / lack the API)."""
    try:
        import jax

        return jax.devices()[0].memory_stats() or None
    except Exception:
        return None


_CAPACITY_KEYS = ("bytes_limit", "bytes_reservable_limit", "pool_bytes")


def device_capacity(stats: Optional[dict] = None) -> Optional[int]:
    """Usable device memory in bytes, or None when the backend exposes no
    allocator stats (the graceful-skip signal for preflight/planning)."""
    if stats is None:
        stats = allocator_stats()
    if not stats:
        return None
    for key in _CAPACITY_KEYS:
        v = stats.get(key)
        if isinstance(v, (int, float)) and v > 0:
            return int(v)
    return None


def tile_peak(snap: dict) -> int:
    """The per-tile peak figure from one snapshot: ``bytes_in_use`` (the
    tile's own live footprint) over the live-buffer sum, with the
    process-lifetime ``peak_bytes_in_use`` high-water mark only as a last
    resort — preferring the monotone counter would attribute the global
    peak to every tile computed after it. Shared by the manifest roll-up
    (`runlog.log_tile_mem`) and the events-only fold (`report._mem_fold`)
    so the two data paths can never diverge."""
    return int(
        snap.get("bytes_in_use")
        or snap.get("live_buffer_bytes")
        or snap.get("peak_bytes_in_use")
        or 0
    )


def host_available_bytes() -> Optional[int]:
    """Host ``MemAvailable`` in bytes (/proc/meminfo), or None where the
    kernel does not expose it (non-Linux). The capacity signal for
    planning on capacity-less CPU backends, where `device_capacity` has
    nothing to report — the "device" memory IS host memory there."""
    try:
        with open("/proc/meminfo") as fh:
            for line in fh:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return None


def snapshot(stats: Optional[dict] = None) -> dict:
    """One attribution snapshot: whatever is observable right now. Keys are
    present only when their source answered — consumers must treat every
    field as optional (CPU runs carry only ``live_buffer_bytes``)."""
    snap: dict = {}
    live = live_bytes()
    if live is not None:
        snap["live_buffer_bytes"] = live
    if stats is None:
        stats = allocator_stats()
    if stats:
        for k in ("bytes_in_use", "peak_bytes_in_use"):
            if k in stats:
                snap[k] = int(stats[k])
        cap = device_capacity(stats)
        if cap is not None:
            snap["bytes_limit"] = cap
    return snap


# ---------------------------------------------------------------------------
# Analytical footprints (preflight layer)
# ---------------------------------------------------------------------------


def footprint_from_analysis(mem_analysis) -> dict:
    """Normalize an XLA ``memory_analysis()`` object into the footprint dict
    the preflight/planner consume (missing attributes read as 0)."""
    fp = {}
    for attr, key in (
        ("argument_size_in_bytes", "arg_bytes"),
        ("output_size_in_bytes", "out_bytes"),
        ("temp_size_in_bytes", "temp_bytes"),
        ("generated_code_size_in_bytes", "code_bytes"),
    ):
        v = getattr(mem_analysis, attr, None)
        fp[key] = int(v) if v is not None else 0
    fp["total_bytes"] = fp["arg_bytes"] + fp["out_bytes"] + fp["temp_bytes"]
    return fp


def aot_footprint(fn, *args) -> dict:
    """Analytical footprint of ``fn(*args)`` via the AOT path: lower +
    compile (no execution, no data movement — ``args`` may be
    `jax.ShapeDtypeStruct`s), then read ``memory_analysis()``. Raises on
    un-lowerable functions; callers decide whether that is fatal."""
    compiled = fn.lower(*args).compile()
    return footprint_from_analysis(compiled.memory_analysis())


class MemoryPreflightError(RuntimeError):
    """A dispatch whose analytical footprint exceeds the memory budget —
    raised BEFORE any device work (fail closed beats an XLA OOM mid-sweep)."""


def preflight(
    label: str,
    footprint: Optional[dict],
    capacity: Optional[int] = None,
    headroom_frac: Optional[float] = None,
    skip_reason: Optional[str] = None,
) -> dict:
    """Compare an analytical ``footprint`` against the device budget.

    Returns a verdict record ``{label, verdict, footprint_bytes,
    capacity_bytes, budget_bytes, headroom}`` with verdict ``"ok"``,
    ``"exceeds"``, or ``"skipped"`` (no capacity or no footprint — CPU and
    backends without ``memory_stats()``). The record is emitted as a
    ``preflight`` obs event and folded into the run manifest's ``memory``
    block when telemetry is on. Callers that must fail closed raise
    `MemoryPreflightError` on ``"exceeds"`` (see `check_preflight`).
    """
    if headroom_frac is None:
        headroom_frac = headroom()
    if capacity is None:
        capacity = device_capacity()
    rec: dict = {"label": label, "headroom": round(float(headroom_frac), 4)}
    if capacity is None or not footprint:
        rec["verdict"] = "skipped"
        rec["reason"] = skip_reason or ("no-capacity" if capacity is None else "no-footprint")
    else:
        budget = int(capacity * headroom_frac)
        need = int(footprint.get("total_bytes", 0))
        rec.update(
            verdict="ok" if need <= budget else "exceeds",
            footprint_bytes=need,
            capacity_bytes=int(capacity),
            budget_bytes=budget,
            arg_bytes=int(footprint.get("arg_bytes", 0)),
            out_bytes=int(footprint.get("out_bytes", 0)),
            temp_bytes=int(footprint.get("temp_bytes", 0)),
            # "aot" = exact XLA memory_analysis; "planner-model" = the
            # fitted fixed+per-cell extrapolation (tile_shape="auto" path)
            source=footprint.get("source", "aot"),
        )
    _log_preflight(rec)
    return rec


def check_preflight(rec: dict) -> dict:
    """Raise `MemoryPreflightError` on an ``"exceeds"`` verdict (the
    fail-closed wrapper); pass through ``ok``/``skipped`` records."""
    if rec.get("verdict") == "exceeds":
        raise MemoryPreflightError(
            f"{rec.get('label', 'dispatch')}: analytical footprint "
            f"{_fmt_bytes(rec.get('footprint_bytes'))} exceeds the memory budget "
            f"{_fmt_bytes(rec.get('budget_bytes'))} "
            f"({rec.get('headroom'):.0%} of {_fmt_bytes(rec.get('capacity_bytes'))} "
            "device capacity). Shrink the tile (tile_shape=... or "
            "tile_shape=\"auto\"), lower the grid resolution, or raise "
            "SBR_MEM_HEADROOM if the budget is known-conservative."
        )
    return rec


def _log_preflight(rec: dict) -> None:
    """Emit the preflight record as an obs event + manifest roll-up entry
    (no-op when telemetry is off; must never sink the caller)."""
    try:
        from sbr_tpu.obs import runlog

        run = runlog.current_run()
        if run is not None:
            run.log_preflight(rec)
    except Exception:
        pass


def fmt_bytes(v) -> str:
    """Human byte formatter shared with `obs.report` (missing/zero → "-")."""
    if not v or not isinstance(v, (int, float)):
        return "-"
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024 or unit == "GiB":
            return f"{v:.1f} {unit}"
        v /= 1024
    return f"{v:.1f} GiB"


_fmt_bytes = fmt_bytes  # internal alias for the error-message paths above


# ---------------------------------------------------------------------------
# Capacity planner
# ---------------------------------------------------------------------------


def fit_linear_model(points) -> Tuple[float, float]:
    """Fit ``bytes ≈ fixed + per_cell * n_cells`` through two (or more)
    ``(n_cells, bytes)`` probe points (least-squares for >2). The linear
    shape is exact for embarrassingly-parallel vmap grids: per-cell working
    set × cells + program constants."""
    pts = [(float(n), float(b)) for n, b in points]
    if len(pts) == 1:
        n, b = pts[0]
        return 0.0, b / n if n else 0.0
    n_mean = sum(n for n, _ in pts) / len(pts)
    b_mean = sum(b for _, b in pts) / len(pts)
    denom = sum((n - n_mean) ** 2 for n, _ in pts)
    if denom == 0.0:
        return max(0.0, b_mean), 0.0
    per_cell = sum((n - n_mean) * (b - b_mean) for (n, b) in pts) / denom
    per_cell = max(0.0, per_cell)
    fixed = max(0.0, b_mean - per_cell * n_mean)
    return fixed, per_cell


def plan_tile_shape(
    n_b: int,
    n_u: int,
    model: Tuple[float, float],
    capacity: Optional[int],
    headroom_frac: Optional[float] = None,
    min_tile: int = 8,
    max_tile: int = 8192,
    fallback: Tuple[int, int] = (256, 256),
    multiple_of: Tuple[int, int] = (1, 1),
    per_device_divisor: int = 1,
) -> Tuple[Tuple[int, int], dict]:
    """Pick the largest power-of-two square tile whose modeled footprint
    fits within ``headroom × capacity``.

    ``model`` is ``(fixed_bytes, per_cell_bytes)`` from `fit_linear_model`.
    Deterministic by construction: same (grid, model, capacity, headroom)
    ⇒ same shape, so independently-planning multihost peers agree. With no
    ``capacity`` (CPU) the ``fallback`` shape is returned with verdict
    ``"skipped"``. Returns ``((tb, tu), plan_record)``; the record lands in
    the run manifest's ``memory.plan`` block.

    ``per_device_divisor`` (mesh size for sharded tiles) divides the
    modeled CELL count: a tile sharded evenly over N devices puts ~1/N of
    its cells — and hence per-cell working set — on each device, while the
    fixed program overhead stays per-device. Without it, an unsharded
    model vs single-device capacity would undersize sharded tiles by the
    device count.

    ``multiple_of`` carries mesh-axis divisibility (a sharded tile must
    split evenly over the mesh): candidates not divisible by it are
    rejected, and if NO candidate qualifies a `MemoryPreflightError` asks
    for an explicit tile_shape — better than silently violating the mesh
    contract.
    """
    if headroom_frac is None:
        headroom_frac = headroom()
    fixed, per_cell = (float(model[0]), float(model[1]))
    divisor = max(1, int(per_device_divisor))
    base_rec = {
        "requested": "auto",
        "grid": [int(n_b), int(n_u)],
        "model_fixed_bytes": int(fixed),
        "model_per_cell_bytes": round(per_cell, 3),
        "headroom": round(float(headroom_frac), 4),
    }
    if divisor > 1:
        base_rec["per_device_divisor"] = divisor
    if capacity is None:
        shape = (min(fallback[0], _pow2_ceil(n_b)), min(fallback[1], _pow2_ceil(n_u)))
        shape = _round_to_multiple(shape, multiple_of)
        return shape, {
            **base_rec,
            "tile_shape": list(shape),
            "verdict": "skipped",
            "reason": "no-capacity",
        }
    budget = int(capacity * headroom_frac)

    def fits(t: int) -> bool:
        cells = min(t, n_b) * min(t, n_u) / divisor
        return fixed + per_cell * cells <= budget

    candidates = []
    t = min_tile
    while t <= max_tile:
        if t % multiple_of[0] == 0 and t % multiple_of[1] == 0:
            candidates.append(t)
        t *= 2
    candidates = [t for t in candidates if fits(t)]
    if not candidates:
        raise MemoryPreflightError(
            f"capacity planner: no power-of-two tile in [{min_tile}, {max_tile}] "
            f"(divisible by mesh axes {multiple_of}) fits the memory budget "
            f"{_fmt_bytes(budget)} ({headroom_frac:.0%} of {_fmt_bytes(capacity)}) "
            f"with model fixed={_fmt_bytes(fixed)} per_cell={per_cell:.1f} B. "
            "Lower the grid resolution, shrink n_grid, or pass an explicit "
            "tile_shape."
        )
    best = candidates[-1]
    # No point tiling beyond the grid itself: once one tile covers the grid,
    # larger candidates change nothing (min() clamps the modeled cells), so
    # the SMALLEST covering candidate is the canonical deterministic answer.
    for t in candidates:
        if t >= n_b and t >= n_u:
            best = t
            break
    shape = (best, best)
    cells = min(best, n_b) * min(best, n_u) / divisor
    return shape, {
        **base_rec,
        "tile_shape": list(shape),
        "verdict": "ok",
        "capacity_bytes": int(capacity),
        "budget_bytes": budget,
        "modeled_bytes": int(fixed + per_cell * cells),
    }


def plan_from_probes(
    n_b: int,
    n_u: int,
    probe_footprint: Callable[[int, int], dict],
    capacity: Optional[int] = None,
    headroom_frac: Optional[float] = None,
    probe_shapes: Tuple[Tuple[int, int], ...] = ((8, 8), (16, 16)),
    **plan_kwargs,
) -> Tuple[Tuple[int, int], dict]:
    """`plan_tile_shape` with the linear model fitted from small AOT probe
    lowerings (``probe_footprint(tb, tu) -> footprint dict``). With no
    capacity the probes are SKIPPED entirely — on CPU the planner must cost
    nothing but a dict lookup."""
    if capacity is None:
        capacity = device_capacity()
    if capacity is None:
        return plan_tile_shape(
            n_b, n_u, (0.0, 0.0), None, headroom_frac, **plan_kwargs
        )
    points = []
    for tb, tu in probe_shapes:
        fp = probe_footprint(tb, tu)
        points.append((tb * tu, fp.get("total_bytes", 0)))
    shape, rec = plan_tile_shape(
        n_b, n_u, fit_linear_model(points), capacity, headroom_frac, **plan_kwargs
    )
    rec["probe_shapes"] = [list(s) for s in probe_shapes]
    return shape, rec


def _pow2_ceil(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _round_to_multiple(shape, multiple_of) -> Tuple[int, int]:
    """Clamp a shape onto the mesh-divisibility grid (round down to the
    multiple; never below the multiple itself)."""
    out = []
    for dim, m in zip(shape, multiple_of):
        if m <= 1:
            out.append(dim)
        else:
            out.append(max(m, (dim // m) * m))
    return tuple(out)


# ---------------------------------------------------------------------------
# Checkpoint-debris retention (`report gc` satellite)
# ---------------------------------------------------------------------------


def gc_debris(root, lease_ttl_s: float = 900.0) -> list:
    """Prune checkpoint debris left by aborted multihost runs under
    ``root``: every ``quarantine/`` directory (corrupt-tile evidence that an
    explicit gc invocation is entitled to clear), every stale
    ``tile_*.lease`` file — stale meaning its tile ``.npz`` already exists
    (completed steal), its holder's TTL lapsed, or the lease is unreadable
    (torn write from a dead holder) — and every EXPIRED elastic-scheduler
    heartbeat (``host_*.hb`` whose own TTL lapsed, or unreadable). Live
    leases within TTL and live heartbeats are preserved: a running steal
    or a breathing host must not be yanked out from under its holder.
    Returns the removed paths. Pure stdlib — safe from the jax-free report
    CLI."""
    root = Path(root)
    removed: list = []
    if not root.is_dir():
        return removed
    now = time.time()
    for q in sorted(root.rglob("quarantine")):
        if not q.is_dir():
            continue
        try:
            shutil.rmtree(q)
            removed.append(q)
        except OSError:
            pass
    for lease in sorted(root.rglob("tile_*.lease")):
        stale = False
        if lease.with_suffix(".npz").exists():
            stale = True
        else:
            try:
                held = json.loads(lease.read_text())
                ttl = float(held.get("ttl_s", lease_ttl_s))
                stale = (now - float(held.get("ts", 0.0))) >= ttl
            except (OSError, ValueError):
                stale = True  # torn write from a dead holder
        if stale:
            try:
                lease.unlink()
                removed.append(lease)
            except OSError:
                pass
    # Expired heartbeats (resilience.elastic): a host that died without a
    # graceful release ages out via its own recorded TTL; an unreadable
    # heartbeat is a torn write from a dying host — debris either way.
    for hb in sorted(root.rglob("host_*.hb")):
        stale = False
        try:
            rec = json.loads(hb.read_text())
            # Fallback mirrors elastic.DEFAULT_HEARTBEAT_TTL_S (kept as a
            # literal: this module stays stdlib-only for the report CLI;
            # update BOTH if that constant ever changes) — gc must never
            # use a SHORTER ttl than live_hosts(), or it would delete a
            # heartbeat whose host liveness still counts as breathing.
            ttl = float(rec.get("ttl_s", 300.0))
            stale = (now - float(rec.get("ts", 0.0))) >= ttl
        except (OSError, ValueError):
            stale = True
        if stale:
            try:
                hb.unlink()
                removed.append(hb)
            except OSError:
                pass
    # Lease-takeover / heartbeat temp files (`*.lease.<pid>.tmp`,
    # `*.hb.<pid>.tmp`, written just before their os.replace): a surviving
    # one means the writer died mid-rename — always debris.
    for pattern in ("tile_*.lease.*.tmp", "host_*.hb.*.tmp"):
        for tmp in sorted(root.rglob(pattern)):
            try:
                tmp.unlink()
                removed.append(tmp)
            except OSError:
                pass
    return removed
