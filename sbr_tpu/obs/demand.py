"""Workload demand observatory + prefetch advisor (ISSUE 18 tentpole).

Every observability layer so far watches the SYSTEM — runs, health, perf,
memory, latency, traces, numerics. This module watches the WORKLOAD: what
the fleet is actually being asked. Three streams per rolling window
(reusing the serve slot-ring machinery — same ``SBR_SERVE_WINDOW_S``
window, same lock-free epoch-replacement slots as `serve.live`):

- **(β, u) demand histogram** on a FIXED binning aligned to the sweep tile
  grid: ``SBR_DEMAND_BINS``² bins over the Figure-4/5 sweep ranges
  (β ∈ [0.5, 4.0], u ∈ [0.02, 0.9] — the ranges `loadgen.build_pool` and
  the baseline sweeps draw from), out-of-range queries clamped into the
  edge bins. Fixed binning is what makes surfaces MERGEABLE: two workers'
  histograms sum bin-for-bin with no re-binning.
- **Heavy-hitter sketch** (Misra-Gries / SpaceSaving family,
  ``SBR_DEMAND_TOPK`` counters) over query fingerprints — deterministic
  for a given stream, mergeable across workers (itemwise sum, then the
  uniform (k+1)-th-count offset — a symmetric operation, so
  ``merge(a, b) == merge(b, a)`` item for item). Each tracked item carries
  its exact (β, u, scenario, kind) payload, which is what turns "hot
  fingerprint" into an actionable sweep cell.
- **Answer-source labels** per bin (lru / disk / coalesced / computed /
  tilecache), so every hot bin carries its warm/cold coverage split.

On top sits the **prefetch advisor** (`advisor_plan`): a PURE deterministic
function from (merged demand surface × current tile-cache coverage) to a
ranked tile plan — per hot bin, the exact β/u axes of its tracked heavy
hitters, scored by ``demand × (1 − already-covered fraction)``. The plan
document (``advisor_plan.json``) is fingerprint-keyed and byte-stable:
two processes replaying the same stream against the same cache write
identical bytes (the artifact the future mesh-prefetch executor consumes).

Surfaces flow everywhere the audit observatory's verdicts do: a ``demand``
block on ``/statz``, ``sbr_demand_*`` gauges on ``/metrics``, a compact
surface in worker heartbeats (merged by the router into the fleet demand
surface), a rolling ``demand.json`` via `RunContext.live_snapshot`, and
offline replay (``python -m sbr_tpu.obs.demand replay`` over loadgen
``--trace-out`` rows — backfill-tolerant: legacy rows without (β, u) are
counted and skipped, never a crash).

``SBR_DEMAND=0`` (the default) is a STRUCTURAL no-op in the audit style:
this module is never imported by the serving path, the engine holds no
tracker, ``/metrics`` stays byte-free of ``sbr_demand``, zero new XLA
traces, answers bit-identical (regression-tested).

No jax import anywhere: demand accounting is pure host bookkeeping, and
`report demand` / replay must run on CI boxes without waking a backend.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional

# ---------------------------------------------------------------------------
# Env knobs
# ---------------------------------------------------------------------------

#: The fixed demand-grid ranges — the Figure-4/5 sweep ranges shared with
#: `serve.loadgen.build_pool` and the baseline β/u sweeps, so demand bins
#: line up with the tile footprints an elastic sweep would compute.
BETA_RANGE = (0.5, 4.0)
U_RANGE = (0.02, 0.9)

SURFACE_SCHEMA = "sbr-demand/1"
LIVE_SCHEMA = "sbr-demand-live/1"
PLAN_SCHEMA = "sbr-demand-advisor/1"

#: Heartbeat blocks cap their cell table so a wide workload cannot bloat
#: every beat (the sketch already bounds the fingerprint side).
_MAX_HB_CELLS = 64


def enabled() -> bool:
    """Whether the demand observatory is on (``SBR_DEMAND``; default off —
    and off must be a structural no-op, see the module docstring)."""
    return os.environ.get("SBR_DEMAND", "").strip() not in ("", "0")


def topk() -> int:
    """Sketch capacity (``SBR_DEMAND_TOPK``, default 32 counters)."""
    env = os.environ.get("SBR_DEMAND_TOPK", "").strip()
    return max(int(env), 1) if env else 32


def bins_n() -> int:
    """Bins per axis of the (β, u) histogram (``SBR_DEMAND_BINS``,
    default 16 → 256 fixed bins)."""
    env = os.environ.get("SBR_DEMAND_BINS", "").strip()
    return max(int(env), 1) if env else 16


def coverage_floor() -> Optional[float]:
    """The `report demand` gate floor (``SBR_DEMAND_COVERAGE_FLOOR``):
    hot-region warm coverage below it exits 1. None = gate disarmed."""
    env = os.environ.get("SBR_DEMAND_COVERAGE_FLOOR", "").strip()
    return float(env) if env else None


# ---------------------------------------------------------------------------
# Binning + fingerprints
# ---------------------------------------------------------------------------


def bin_of(beta: float, u: float, nb: int) -> tuple:
    """(i, j) bin of one query on the fixed grid; out-of-range coordinates
    clamp into the edge bins so every query lands somewhere."""
    blo, bhi = BETA_RANGE
    ulo, uhi = U_RANGE
    i = int((float(beta) - blo) / (bhi - blo) * nb)
    j = int((float(u) - ulo) / (uhi - ulo) * nb)
    return (min(max(i, 0), nb - 1), min(max(j, 0), nb - 1))


def bin_bounds(i: int, j: int, nb: int) -> dict:
    """The (β, u) rectangle of bin (i, j) — hot-region table rendering."""
    blo, bhi = BETA_RANGE
    ulo, uhi = U_RANGE
    bw = (bhi - blo) / nb
    uw = (uhi - ulo) / nb
    return {
        "beta_lo": round(blo + i * bw, 6), "beta_hi": round(blo + (i + 1) * bw, 6),
        "u_lo": round(ulo + j * uw, 6), "u_hi": round(ulo + (j + 1) * uw, 6),
    }


def query_fingerprint(beta: float, u: float, scenario: str = "default",
                      kind: str = "plain") -> str:
    """Deterministic short fingerprint of one query shape. Hashed from the
    full-precision float reprs (the `params_doc` wire convention: repr
    round-trips exactly), so an engine-side record and an offline replay of
    the same traced query produce the SAME item — the cross-process
    mergeability contract of the sketch."""
    payload = f"{float(beta)!r}|{float(u)!r}|{scenario}|{kind}"
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# Misra-Gries heavy-hitter sketch
# ---------------------------------------------------------------------------


class MisraGries:
    """Misra-Gries heavy-hitter summary with per-item payloads.

    ``k`` counters; any item with true frequency > N/(k+1) is guaranteed
    tracked, and every tracked count undershoots the true count by at most
    N/(k+1). Deterministic for a given stream (the decrement step touches
    ALL counters uniformly — no tie-breaking choice exists), and mergeable
    (`merge`: itemwise sum, then subtract the (k+1)-th largest combined
    count from everything and drop the non-positive — the Agarwal et al.
    mergeable-summaries combine, symmetric in its arguments so
    ``a.merge(b)`` equals ``b.merge(a)`` item for item).

    ``payloads`` carry each tracked item's exact (β, u, scenario, kind) —
    payloads are pure functions of the fingerprint, so merges can never
    conflict (first writer wins, all writers agree)."""

    __slots__ = ("k", "counters", "payloads")

    def __init__(self, k: int) -> None:
        self.k = max(int(k), 1)
        self.counters: Dict[str, int] = {}
        self.payloads: Dict[str, dict] = {}

    def update(self, item: str, payload: Optional[dict] = None, n: int = 1) -> None:
        c = self.counters
        n = int(n)
        if n <= 0:
            return
        if item in c:
            c[item] += n
            return
        while n > 0 and item not in c and len(c) >= self.k:
            # Batched decrement: one pass removes min(remaining, min-count)
            # from every counter (equivalent to that many unit decrements).
            d = min(n, min(c.values()))
            for key in list(c):
                c[key] -= d
                if c[key] <= 0:
                    del c[key]
                    self.payloads.pop(key, None)
            n -= d
        if n > 0:
            c[item] = c.get(item, 0) + n
            if payload is not None:
                self.payloads.setdefault(item, payload)

    def merge(self, other: "MisraGries") -> "MisraGries":
        """Pure combine (neither operand mutated); capacity = max(k, k')."""
        out = MisraGries(max(self.k, other.k))
        summed: Dict[str, int] = dict(self.counters)
        for item, n in other.counters.items():
            summed[item] = summed.get(item, 0) + n
        payloads = dict(other.payloads)
        payloads.update(self.payloads)  # agree by construction; self wins
        if len(summed) > out.k:
            offset = sorted(summed.values(), reverse=True)[out.k]
            summed = {i: n - offset for i, n in summed.items() if n - offset > 0}
        out.counters = summed
        out.payloads = {i: payloads[i] for i in summed if i in payloads}
        return out

    def top(self, n: Optional[int] = None) -> List[tuple]:
        """[(item, count, payload), ...] by descending count, item-sorted
        ties — fully deterministic for rendering and plan building."""
        ranked = sorted(self.counters.items(), key=lambda kv: (-kv[1], kv[0]))
        if n is not None:
            ranked = ranked[:n]
        return [(i, c, self.payloads.get(i)) for i, c in ranked]

    def to_doc(self) -> dict:
        return {
            "k": self.k,
            "items": [[i, c, p] for i, c, p in self.top()],
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "MisraGries":
        out = cls(int(doc.get("k") or 1))
        for entry in doc.get("items") or []:
            try:
                item, count = str(entry[0]), int(entry[1])
                payload = entry[2] if len(entry) > 2 else None
            except (TypeError, ValueError, IndexError):
                continue
            if count > 0:
                out.counters[item] = count
                if isinstance(payload, dict):
                    out.payloads[item] = payload
        return out


# ---------------------------------------------------------------------------
# Demand surfaces (the mergeable document)
# ---------------------------------------------------------------------------


def _surface_doc(counts: Dict[str, int], sources: Dict[str, Dict[str, int]],
                 sketch: MisraGries, nb: int, max_cells: Optional[int] = None) -> dict:
    cells = {}
    ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
    if max_cells is not None:
        ranked = ranked[:max_cells]
    for key, n in ranked:
        cells[key] = {
            "count": int(n),
            "sources": {s: int(v) for s, v in sorted((sources.get(key) or {}).items())},
        }
    return {
        "schema": SURFACE_SCHEMA,
        "bins": int(nb),
        "beta_range": list(BETA_RANGE),
        "u_range": list(U_RANGE),
        "queries": int(sum(counts.values())),
        "cells": cells,
        "sketch": sketch.to_doc(),
    }


def merge_surfaces(surfaces: List[dict]) -> dict:
    """Fold N demand surfaces (worker heartbeat blocks, per-run totals)
    into one — the router's fleet merge and `report demand`'s multi-run
    merge. Surfaces on a different bin grid are skipped (counted in
    ``skipped_surfaces``): fixed binning is the merge contract; silently
    re-binning would smear the heatmap."""
    surfaces = [s for s in surfaces if isinstance(s, dict)]
    nb = None
    for s in surfaces:
        if isinstance(s.get("bins"), int):
            nb = s["bins"]
            break
    if nb is None:
        nb = bins_n()
    counts: Dict[str, int] = {}
    sources: Dict[str, Dict[str, int]] = {}
    sketch = MisraGries(topk())
    skipped = 0
    for s in surfaces:
        if s.get("bins") != nb:
            skipped += 1
            continue
        for key, cell in (s.get("cells") or {}).items():
            try:
                n = int(cell.get("count", 0))
            except (TypeError, AttributeError, ValueError):
                continue
            counts[key] = counts.get(key, 0) + n
            dst = sources.setdefault(key, {})
            for src, v in (cell.get("sources") or {}).items():
                dst[src] = dst.get(src, 0) + int(v)
        sketch = sketch.merge(MisraGries.from_doc(s.get("sketch") or {}))
    out = _surface_doc(counts, sources, sketch, nb)
    if skipped:
        out["skipped_surfaces"] = skipped
    return out


_WARM_SOURCES = ("lru", "disk", "coalesced", "tilecache")


def _cell_warm(cell: dict) -> int:
    srcs = cell.get("sources") or {}
    return sum(int(srcs.get(s, 0)) for s in _WARM_SOURCES)


def hot_bins(surface: dict, mass: float = 0.5) -> List[dict]:
    """The hot region: the smallest count-ranked set of bins covering at
    least ``mass`` of the window's queries (ties broken by bin key — fully
    deterministic). Each entry carries its warm/cold split from the
    answer-source labels."""
    cells = surface.get("cells") or {}
    total = sum(int(c.get("count", 0)) for c in cells.values())
    if total <= 0:
        return []
    nb = int(surface.get("bins") or bins_n())
    ranked = sorted(cells.items(), key=lambda kv: (-int(kv[1].get("count", 0)), kv[0]))
    out, cum = [], 0
    for key, cell in ranked:
        n = int(cell.get("count", 0))
        if n <= 0:
            break
        warm = _cell_warm(cell)
        try:
            i, j = (int(v) for v in key.split(","))
        except ValueError:
            continue
        out.append({
            "bin": key,
            **bin_bounds(i, j, nb),
            "count": n,
            "share": round(n / total, 4),
            "warm": warm,
            "warm_coverage": round(warm / n, 4),
        })
        cum += n
        if cum >= mass * total:
            break
    return out


# ---------------------------------------------------------------------------
# Tile-cache coverage + the prefetch advisor
# ---------------------------------------------------------------------------


def coverage_from_cache_dir(cache_dir) -> Optional[dict]:
    """Scan a tile-cache root's ``*.meta.json`` cell-index sidecars
    (`resilience.elastic.tile_meta`) into the advisor's coverage input:
    the exact (β, u) cells the cache can already answer. Torn or alien
    sidecars are skipped (the `TileCacheBridge._scan` tolerance). None
    when the root does not exist (no cache configured ≠ an empty cache)."""
    root = Path(cache_dir)
    if not root.is_dir():
        return None
    pairs = set()
    entries = 0
    for meta_path in sorted(root.rglob("*.meta.json")):
        try:
            meta = json.loads(meta_path.read_text())
            betas = [float(b) for b in meta["betas"]]
            us = [float(u) for u in meta["us"]]
        except (OSError, ValueError, KeyError, TypeError):
            continue
        entries += 1
        for b in betas:
            for u in us:
                pairs.add((b, u))
    return {
        "entries": entries,
        "pairs": sorted([b, u] for b, u in pairs),
    }


def _coverage_pairs(coverage: Optional[dict]) -> set:
    out = set()
    for pair in (coverage or {}).get("pairs") or []:
        try:
            out.add((float(pair[0]), float(pair[1])))
        except (TypeError, ValueError, IndexError):
            continue
    return out


def advisor_plan(surface: dict, coverage: Optional[dict] = None,
                 max_tiles: int = 8, floor: Optional[float] = None) -> dict:
    """The prefetch advisor: PURE deterministic function from (merged
    demand surface × tile-cache coverage) to a ranked tile plan.

    Per hot bin, the tile is the exact sorted β/u axes of the sketch's
    tracked heavy hitters inside the bin — precisely the cells a sweep
    must compute for `TileCacheBridge.lookup`'s exact-membership match to
    serve them warm. Tiles are scored ``demand_weight × (1 − covered
    fraction)`` (a fully covered hot bin ranks zero — nothing to
    prefetch) and ranked by (-score, bin). The plan is fingerprint-keyed
    (sha256 over its canonical JSON) and byte-stable: no timestamps, keys
    sorted — two processes replaying the same stream against the same
    cache write identical bytes."""
    hot = hot_bins(surface)
    nb = int(surface.get("bins") or bins_n())
    covered = _coverage_pairs(coverage)
    sketch = MisraGries.from_doc(surface.get("sketch") or {})
    by_bin: Dict[str, list] = {}
    for item, count, payload in sketch.top():
        if not isinstance(payload, dict):
            continue
        try:
            b, u = float(payload["beta"]), float(payload["u"])
        except (KeyError, TypeError, ValueError):
            continue
        i, j = bin_of(b, u, nb)
        by_bin.setdefault(f"{i},{j}", []).append((item, count, b, u))
    tiles = []
    for entry in hot:
        items = by_bin.get(entry["bin"]) or []
        betas = sorted({b for _, _, b, _ in items})
        us = sorted({u for _, _, _, u in items})
        weight = sum(c for _, c, _, _ in items)
        covered_weight = sum(c for _, c, b, u in items if (b, u) in covered)
        tile_cov = round(covered_weight / weight, 4) if weight else 0.0
        score = entry["count"] * (1.0 - tile_cov)
        tiles.append({
            "bin": entry["bin"],
            "count": entry["count"],
            "warm_coverage": entry["warm_coverage"],
            "tile_coverage": tile_cov,
            "score": round(score, 4),
            "betas": betas,
            "us": us,
            "cells": len(betas) * len(us),
            "fingerprints": [i for i, _, _, _ in items],
        })
    tiles.sort(key=lambda t: (-t["score"], t["bin"]))
    tiles = tiles[:max_tiles]
    for rank, t in enumerate(tiles, start=1):
        t["rank"] = rank
    plan = {
        "schema": PLAN_SCHEMA,
        "bins": nb,
        "beta_range": list(BETA_RANGE),
        "u_range": list(U_RANGE),
        "surface_queries": int(surface.get("queries") or 0),
        "coverage_floor": floor,
        "cache_entries": (coverage or {}).get("entries"),
        "tiles": tiles,
    }
    plan["plan_fingerprint"] = hashlib.sha256(
        json.dumps(plan, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:16]
    return plan


def plan_bytes(plan: dict) -> bytes:
    """The canonical byte form of a plan — what `write_plan` lands and the
    cross-process determinism witness compares."""
    return (json.dumps(plan, sort_keys=True, separators=(",", ":")) + "\n").encode()


def write_plan(plan: dict, path) -> Path:
    """Atomically write ``advisor_plan.json`` (tmp + rename, the manifest
    discipline) in its canonical byte form."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_bytes(plan_bytes(plan))
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# The streaming tracker (engine-side)
# ---------------------------------------------------------------------------


class _Slot:
    """One time slot of the rolling demand window (the `serve.live`
    epoch-replacement idiom: recording touches only the current slot,
    stale slots are replaced wholesale by one reference assignment)."""

    __slots__ = ("epoch", "counts", "sources", "sketch")

    def __init__(self, epoch: int, k: int) -> None:
        self.epoch = epoch
        self.counts: Dict[str, int] = {}
        self.sources: Dict[str, Dict[str, int]] = {}
        self.sketch = MisraGries(k)


class DemandTracker:
    """Streaming workload observatory for one serving engine.

    Windowing reuses the serve slot machinery: the window
    (``SBR_SERVE_WINDOW_S``, default 60 s) divides into the same 12 slots
    `serve.live.LiveMetrics` uses, with the same lock-free contract (the
    hot path runs on the single batcher thread; a scrape racing a slot
    rotation folds either the old or the new slot, never corrupts state).
    Lifetime totals accumulate beside the window for `report demand`.

    ``time_fn`` is injectable so tests drive window expiry without
    sleeping. ``coverage_fn`` (optional, engine-supplied) feeds the
    advisor the live tile-cache coverage at snapshot time."""

    def __init__(self, window_s: Optional[float] = None,
                 bins: Optional[int] = None, topk_n: Optional[int] = None,
                 time_fn=time.monotonic, run=None, coverage_fn=None) -> None:
        from sbr_tpu.serve import live as _live

        self.window_s = float(window_s) if window_s else _live.window_seconds()
        self._n_slots = _live._N_SLOTS
        self._slot_s = self.window_s / self._n_slots
        self.bins = int(bins) if bins else bins_n()
        self.k = int(topk_n) if topk_n else topk()
        self._time = time_fn
        self._run = run
        self._coverage_fn = coverage_fn
        self._slots = [_Slot(-1, self.k) for _ in range(self._n_slots)]
        self.totals_counts: Dict[str, int] = {}
        self.totals_sources: Dict[str, Dict[str, int]] = {}
        self.totals_sketch = MisraGries(self.k)
        self.queries_total = 0
        self._last_write = 0.0
        self._last_rotate = self._time()
        self._rotations = 0

    # -- recording (engine batcher thread) ----------------------------------
    def _slot(self) -> _Slot:
        epoch = int(self._time() / self._slot_s)
        pos = epoch % self._n_slots
        slot = self._slots[pos]
        if slot.epoch != epoch:
            slot = _Slot(epoch, self.k)
            self._slots[pos] = slot
        return slot

    def record(self, beta: float, u: float, scenario: str = "default",
               kind: str = "plain", source: str = "computed") -> None:
        """One served query. Never raises: demand telemetry must not sink
        the serving path (the `LiveMetrics` contract)."""
        try:
            i, j = bin_of(beta, u, self.bins)
            key = f"{i},{j}"
            fp = query_fingerprint(beta, u, scenario, kind)
            payload = {"beta": float(beta), "u": float(u),
                       "scenario": scenario, "kind": kind}
            slot = self._slot()
            slot.counts[key] = slot.counts.get(key, 0) + 1
            srcs = slot.sources.setdefault(key, {})
            srcs[source] = srcs.get(source, 0) + 1
            slot.sketch.update(fp, payload)
            self.totals_counts[key] = self.totals_counts.get(key, 0) + 1
            tsrcs = self.totals_sources.setdefault(key, {})
            tsrcs[source] = tsrcs.get(source, 0) + 1
            self.totals_sketch.update(fp, payload)
            self.queries_total += 1
        except Exception:
            pass

    def record_params(self, params, scenario: str = "default",
                      source: str = "computed", grads: bool = False,
                      kind: Optional[str] = None) -> None:
        """Engine hook: one fulfilled query (β/u read off the params). The
        kind defaults to the grads flag; composed routes pass their own
        ("scenario" / "population")."""
        try:
            self.record(
                params.learning.beta, params.economic.u, scenario=scenario,
                kind=kind or ("grads" if grads else "plain"), source=source,
            )
        except Exception:
            pass

    # -- reading ------------------------------------------------------------
    def _window_fold(self) -> tuple:
        """(counts, sources, sketch) over the live slots, folded in epoch
        order — ONE fold per exposition, deterministic slot order."""
        min_epoch = int(self._time() / self._slot_s) - self._n_slots + 1
        counts: Dict[str, int] = {}
        sources: Dict[str, Dict[str, int]] = {}
        sketch = MisraGries(self.k)
        for slot in sorted(list(self._slots), key=lambda s: s.epoch):
            if slot.epoch < min_epoch:
                continue
            for key, n in list(slot.counts.items()):
                counts[key] = counts.get(key, 0) + n
            for key, srcs in list(slot.sources.items()):
                dst = sources.setdefault(key, {})
                for s, v in list(srcs.items()):
                    dst[s] = dst.get(s, 0) + v
            sketch = sketch.merge(slot.sketch)
        return counts, sources, sketch

    def window_surface(self) -> dict:
        counts, sources, sketch = self._window_fold()
        out = _surface_doc(counts, sources, sketch, self.bins)
        out["window_s"] = self.window_s
        return out

    def totals_surface(self) -> dict:
        return _surface_doc(
            self.totals_counts, self.totals_sources, self.totals_sketch, self.bins
        )

    def snapshot(self) -> dict:
        """The `/statz` demand block and the rolling ``demand.json`` body
        (minus the `ts` stamp the writer adds)."""
        window = self.window_surface()
        totals = self.totals_surface()
        return {
            "schema": LIVE_SCHEMA,
            "bins": self.bins,
            "topk": self.k,
            "queries_total": self.queries_total,
            "window": window,
            "totals": totals,
            "hot_bins": hot_bins(window),
        }

    def heartbeat_block(self) -> dict:
        """The compact surface riding worker heartbeats (what the router
        merges into the fleet demand surface). The cell table caps at the
        hottest `_MAX_HB_CELLS` bins; the sketch is already k-bounded."""
        counts, sources, sketch = self._window_fold()
        return _surface_doc(counts, sources, sketch, self.bins,
                            max_cells=_MAX_HB_CELLS)

    def prometheus_lines(self) -> list:
        """``sbr_demand_*`` exposition. SBR_DEMAND=0 engines contribute
        NOTHING (the tracker doesn't exist) — tests assert the exposition
        is byte-free of the prefix when demand is off."""
        window = self.window_surface()
        hot = hot_bins(window)
        hot_q = sum(h["count"] for h in hot)
        hot_warm = sum(h["warm"] for h in hot)
        cov = hot_warm / hot_q if hot_q else 0.0
        return [
            "# TYPE sbr_demand_queries_total counter",
            f"sbr_demand_queries_total {self.queries_total}",
            "# TYPE sbr_demand_window_queries gauge",
            f"sbr_demand_window_queries {window['queries']}",
            "# TYPE sbr_demand_hot_bins gauge",
            f"sbr_demand_hot_bins {len(hot)}",
            "# TYPE sbr_demand_hot_warm_coverage gauge",
            f"sbr_demand_hot_warm_coverage {cov:g}",
            "# TYPE sbr_demand_sketch_items gauge",
            f"sbr_demand_sketch_items {len(window['sketch']['items'])}",
        ]

    # -- rolling snapshot + advisor artifact --------------------------------
    def _rotate_s(self) -> float:
        env = os.environ.get("SBR_DEMAND_ROTATE_S", "").strip()
        return float(env) if env else 0.0

    def maybe_write(self, run, min_interval_s: float = 0.5,
                    force: bool = False) -> bool:
        """Write the rolling ``demand.json`` through ``run.live_snapshot``
        at a bounded cadence (``force`` for the final write at engine
        close, which also lands ``advisor_plan.json``). With
        ``SBR_DEMAND_ROTATE_S`` set, the previous snapshot is archived as
        ``demand.NNN.json`` before each rotation-due overwrite (what
        ``report gc --demand-keep`` prunes). Never raises."""
        if run is None:
            return False
        now = self._time()
        if not force and now - self._last_write < min_interval_s:
            return False
        self._last_write = now
        try:
            rotate_s = self._rotate_s()
            if rotate_s > 0 and now - self._last_rotate >= rotate_s:
                self._archive_snapshot(run)
                self._last_rotate = now
            doc = self.snapshot()
            doc["ts"] = round(time.time(), 3)
            run.live_snapshot(doc, name="demand.json")
            if force:
                coverage = None
                if self._coverage_fn is not None:
                    try:
                        coverage = self._coverage_fn()
                    except Exception:
                        coverage = None
                plan = advisor_plan(self.totals_surface(), coverage,
                                    floor=coverage_floor())
                write_plan(plan, Path(run.run_dir) / "advisor_plan.json")
                try:
                    run.log_demand("plan", tiles=len(plan["tiles"]),
                                   fingerprint=plan["plan_fingerprint"])
                except Exception:
                    pass
            return True
        except Exception:
            return False

    def _archive_snapshot(self, run) -> None:
        """Archive the active ``demand.json`` as the next free
        ``demand.NNN.json`` (rotation — the gc candidates)."""
        active = Path(run.run_dir) / "demand.json"
        if not active.exists():
            return
        idx = self._rotations
        while (Path(run.run_dir) / f"demand.{idx:03d}.json").exists():
            idx += 1
        (Path(run.run_dir) / f"demand.{idx:03d}.json").write_bytes(
            active.read_bytes()
        )
        self._rotations = idx + 1
        try:
            run.log_demand("rotate", index=idx)
        except Exception:
            pass

    def close(self, run) -> None:
        """Final force-write at engine close (rolling snapshot + advisor
        plan artifact)."""
        self.maybe_write(run, force=True)


# ---------------------------------------------------------------------------
# Retention (report gc --demand-keep)
# ---------------------------------------------------------------------------


def gc_demand_files(root, keep: int = 4,
                    running_grace_s: float = 6 * 3600.0) -> list:
    """Prune rotated demand snapshots (``demand.NNN.json``) and aged
    advisor plans (``advisor_plan.NNN.json``) inside each run dir under
    ``root`` down to the newest ``keep`` per kind, mirroring the
    ``--trace-keep`` / ``--audit-keep`` contract: live runs (manifest
    "running" with recent mtime) are never touched, and the ACTIVE
    ``demand.json`` / ``advisor_plan.json`` are never candidates (the
    globs require the rotation's second dot). Returns removed paths."""
    from sbr_tpu.obs import runlog

    keep = max(int(keep), 0)
    removed: list = []
    root = Path(root)
    if not root.is_dir():
        return removed
    for d in sorted(p for p in root.iterdir() if p.is_dir()):
        if runlog._run_is_live(d, running_grace_s):
            continue
        for pattern in ("demand.*.json", "advisor_plan.*.json"):
            rotated = sorted(d.glob(pattern))
            for path in rotated[: max(len(rotated) - keep, 0)]:
                try:
                    path.unlink()
                    removed.append(str(path))
                except OSError:
                    pass
    return removed


# ---------------------------------------------------------------------------
# Offline replay (loadgen --trace-out rows)
# ---------------------------------------------------------------------------


def replay_rows(rows, bins: Optional[int] = None,
                topk_n: Optional[int] = None) -> tuple:
    """Rebuild a demand surface from loadgen ``--trace-out`` rows.

    Backfill-tolerant reader (the satellite contract): legacy rows without
    the (β, u) coordinates — written before ISSUE 18 — are counted in
    ``legacy_rows`` and skipped, never a crash; rows without an answer
    source label land under source "unknown" (cold). Returns
    ``(surface, stats)``. No wall-clock anywhere: replaying the same
    stream twice (in two processes) yields an identical surface — the
    byte-identical advisor-plan witness builds on this."""
    nb = int(bins) if bins else bins_n()
    k = int(topk_n) if topk_n else topk()
    counts: Dict[str, int] = {}
    sources: Dict[str, Dict[str, int]] = {}
    sketch = MisraGries(k)
    stats = {"rows": 0, "replayed": 0, "legacy_rows": 0, "bad_rows": 0}
    for row in rows:
        stats["rows"] += 1
        if not isinstance(row, dict):
            stats["bad_rows"] += 1
            continue
        beta, u = row.get("beta"), row.get("u")
        if not (isinstance(beta, (int, float)) and isinstance(u, (int, float))
                and math.isfinite(beta) and math.isfinite(u)):
            stats["legacy_rows"] += 1
            continue
        scenario = str(row.get("scenario") or "mix")
        kind = str(row.get("kind") or "plain")
        source = str(row.get("source") or "unknown")
        i, j = bin_of(beta, u, nb)
        key = f"{i},{j}"
        counts[key] = counts.get(key, 0) + 1
        srcs = sources.setdefault(key, {})
        srcs[source] = srcs.get(source, 0) + 1
        sketch.update(
            query_fingerprint(beta, u, scenario, kind),
            {"beta": float(beta), "u": float(u),
             "scenario": scenario, "kind": kind},
        )
        stats["replayed"] += 1
    return _surface_doc(counts, sources, sketch, nb), stats


def _iter_trace_rows(paths):
    """JSONL rows from loadgen ``--trace-out`` files; torn lines are
    yielded as None (counted as bad rows by `replay_rows`)."""
    for path in paths:
        with open(path, "r") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    yield None


def _main_replay(argv) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.demand replay",
        description="Rebuild a demand surface (and optionally the advisor "
        "plan) from loadgen --trace-out JSONL rows; deterministic — two "
        "replays of the same stream write byte-identical plans",
    )
    parser.add_argument("traces", nargs="+", help="loadgen --trace-out file(s)")
    parser.add_argument("--bins", type=int, default=None,
                        help="bins per axis (default SBR_DEMAND_BINS or 16)")
    parser.add_argument("--topk", type=int, default=None,
                        help="sketch capacity (default SBR_DEMAND_TOPK or 32)")
    parser.add_argument("--cache-dir", default=None, dest="cache_dir",
                        help="tile-cache root whose *.meta.json sidecars "
                        "feed the advisor's coverage input")
    parser.add_argument("--plan-out", default=None, dest="plan_out",
                        help="write the ranked advisor plan here "
                        "(canonical bytes — the determinism witness)")
    parser.add_argument("--out", default=None,
                        help="also write the rebuilt surface JSON here")
    parser.add_argument("--floor", type=float, default=None,
                        help="gate: exit 1 when hot-region warm coverage "
                        "is under FLOOR (default: no gate)")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    for p in args.traces:
        if not Path(p).is_file():
            print(f"error: not a trace file: {p}", file=sys.stderr)
            return 2
    surface, stats = replay_rows(
        _iter_trace_rows(args.traces), bins=args.bins, topk_n=args.topk
    )
    if stats["replayed"] == 0:
        print("no replayable rows (no (beta, u) coordinates — pre-ISSUE-18 "
              "trace, or empty file)", file=sys.stderr)
        return 3
    coverage = coverage_from_cache_dir(args.cache_dir) if args.cache_dir else None
    plan = advisor_plan(surface, coverage, floor=args.floor)
    if args.plan_out:
        write_plan(plan, args.plan_out)
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(surface, sort_keys=True) + "\n")
    hot = hot_bins(surface)
    hot_q = sum(h["count"] for h in hot)
    hot_warm = sum(h["warm"] for h in hot)
    cov = hot_warm / hot_q if hot_q else 0.0
    doc = {
        "stats": stats,
        "queries": surface["queries"],
        "hot_bins": hot,
        "hot_warm_coverage": round(cov, 4),
        "plan_fingerprint": plan["plan_fingerprint"],
        "planned_tiles": len(plan["tiles"]),
    }
    if args.json:
        print(json.dumps(doc, sort_keys=True))
    else:
        print(f"replayed {stats['replayed']}/{stats['rows']} row(s) "
              f"({stats['legacy_rows']} legacy, {stats['bad_rows']} bad) -> "
              f"{len(hot)} hot bin(s), warm coverage {cov:.3f}, "
              f"plan {plan['plan_fingerprint']} "
              f"({len(plan['tiles'])} tile(s))")
    if args.floor is not None and cov < args.floor:
        print(f"hot-region warm coverage {cov:.3f} under floor "
              f"{args.floor:g}", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "replay":
        return _main_replay(argv[1:])
    print("usage: python -m sbr_tpu.obs.demand replay TRACE.jsonl... "
          "[--plan-out PLAN] [--cache-dir DIR] [--json]", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
