"""Render an obs run directory as a human-readable timing/throughput table,
diff two runs, gate on numerical health or perf regressions, or
garbage-collect old runs.

Usage:
    python -m sbr_tpu.obs.report RUN_DIR            # render one run
    python -m sbr_tpu.obs.report RUN_DIR OTHER_DIR  # diff two runs
    python -m sbr_tpu.obs.report RUN_DIR --events 20  # also tail raw events
    python -m sbr_tpu.obs.report health RUN_DIR     # numerical-health report;
                                                    # exits 1 on divergence,
                                                    # 3 if no health data
    python -m sbr_tpu.obs.report resilience RUN_DIR # fault/retry/repair report;
                                                    # exits 1 on unrecovered
                                                    # failures
    python -m sbr_tpu.obs.report trend [HISTORY]    # perf-history timelines
    python -m sbr_tpu.obs.report trend --check --tolerance 0.15
                                                    # regression gate: exit 1
                                                    # beyond tolerance, 3 on
                                                    # missing/short history
    python -m sbr_tpu.obs.report memory RUN_DIR     # per-span/per-tile peak-
                                                    # memory attribution; exit
                                                    # 1 when a tile exceeds
                                                    # the headroom threshold,
                                                    # 3 on missing data
    python -m sbr_tpu.obs.report serve RUN_DIR      # live serving telemetry
                                                    # (rolling live.json of a
                                                    # running or finished
                                                    # sbr_tpu.serve engine);
                                                    # exit 1 on SLO breach
                                                    # (p99 over
                                                    # SBR_SERVE_SLO_MS, cache
                                                    # hit rate under floor),
                                                    # 3 on missing data
    python -m sbr_tpu.obs.report elastic RUN_DIR    # elastic-scheduler census
                                                    # (hosts joined/left, tile
                                                    # claims by source, global
                                                    # tile-cache outcomes);
                                                    # exit 3 when no scheduler
                                                    # events were recorded
    python -m sbr_tpu.obs.report fleet RUN_DIR      # serving-fleet report
                                                    # (router fleet.json +
                                                    # fleet events: failovers,
                                                    # hedges, sheds, breaker
                                                    # states); exit 1 on lost
                                                    # queries or a breaker
                                                    # stuck open, 3 when no
                                                    # fleet data was recorded
    python -m sbr_tpu.obs.report audit RUN_DIR      # numerics-audit report
                                                    # (canary probe verdicts
                                                    # + cycle roll-ups from
                                                    # sbr_tpu.obs.audit);
                                                    # exit 1 on any drift
                                                    # verdict, 3 when no
                                                    # audit data recorded
    python -m sbr_tpu.obs.report demand DIR [DIR..] # workload-demand report
                                                    # (rolling demand.json
                                                    # surfaces: hot (beta,u)
                                                    # bins, heavy hitters,
                                                    # warm coverage, ranked
                                                    # prefetch-advisor plan);
                                                    # exit 1 when hot-region
                                                    # warm coverage is under
                                                    # the floor, 3 when no
                                                    # demand data recorded
    python -m sbr_tpu.obs.report trace DIR [DIR..]  # fleet-wide trace join
                                                    # (router + worker run
                                                    # dirs): per-query span
                                                    # waterfalls; exit 1 when
                                                    # a sampled trace has
                                                    # orphaned/unjoinable
                                                    # spans, 3 with no spans
    python -m sbr_tpu.obs.report slo DIR [DIR..]    # SLO observatory over
                                                    # trace spans: per-layer
                                                    # latency breakdowns,
                                                    # breach exemplar tables,
                                                    # hedge/failover
                                                    # causality; exit 1 on a
                                                    # breach, 3 with nothing
                                                    # to judge
    python -m sbr_tpu.obs.report gc [ROOT] --keep N # prune old run dirs +
                                                    # checkpoint debris
                                                    # (quarantine/, stale
                                                    # tile_*.lease files,
                                                    # expired host_*.hb
                                                    # heartbeats); with
                                                    # --tile-cache DIR
                                                    # --keep-days N also
                                                    # prunes cold global-
                                                    # cache entries; with
                                                    # --audit-keep N also
                                                    # prunes aged audit
                                                    # batteries + archived
                                                    # goldens; with
                                                    # --demand-keep N also
                                                    # prunes rotated demand
                                                    # snapshots + aged
                                                    # advisor plans

Every reporting subcommand (timing render, diff, health, trend) takes
``--json`` and then prints one machine-readable JSON document instead of
tables — CI and scripts consume that rather than scraping text.

The ``health`` subcommand renders the `sbr_tpu.diag` census (worst-cell
tables, NaN/fallback flag counts, residual histograms) recorded by
`obs.log_health`, and its exit code is the CI gate: nonzero whenever any
cell carries a divergent flag (NaN poison, non-finite residual,
fixed-point non-convergence). The ``trend`` subcommand is the matching
PERF gate over `sbr_tpu.obs.history`'s append-only ``bench_history.jsonl``
(see that module for baseline/polarity semantics).

Reads only `manifest.json` + `events.jsonl` (or the history JSONL) — no
JAX import, so the report never touches (or hangs on) an accelerator
backend.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path


def load_run(run_dir) -> dict:
    """Load a run directory: manifest (required) + parsed events (optional).

    Tolerates torn event lines (ISSUE 7 satellite): a process killed
    mid-write leaves a truncated final line — possibly cut inside a UTF-8
    multibyte sequence, so even ``read_text()`` can raise — or a line that
    parses but is not an event object. Every such line is counted in
    ``bad_event_lines`` (surfaced in report headers) instead of crashing
    the report, and folding continues over the intact events.
    """
    run_dir = Path(run_dir)
    manifest_path = run_dir / "manifest.json"
    if not manifest_path.exists():
        raise FileNotFoundError(f"{manifest_path} not found — not an obs run directory")
    manifest = json.loads(manifest_path.read_text())
    events = []
    bad_lines = 0
    events_path = run_dir / "events.jsonl"
    if events_path.exists():
        # bytes + replace: a torn multibyte character must not take down
        # the whole log (strict read_text raises UnicodeDecodeError).
        text = events_path.read_bytes().decode("utf-8", errors="replace")
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                bad_lines += 1
                continue
            if isinstance(ev, dict):
                events.append(ev)
            else:
                bad_lines += 1  # parseable but not an event object
    return {
        "dir": str(run_dir),
        "manifest": manifest,
        "events": events,
        "bad_event_lines": bad_lines,
    }


def _bad_lines_note(run: dict) -> str:
    """Header suffix surfacing tolerated torn event lines (empty if none)."""
    n = run.get("bad_event_lines", 0)
    return f"   ({n} unparseable event line(s) skipped — torn write?)" if n else ""


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    return f"{v * 1e3:.1f} ms" if v < 1.0 else f"{v:.3f} s"


# obs.mem is stdlib-only at module scope, so this import cannot initialize
# an accelerator backend (running via `python -m` already imports the jax
# MODULE through the parent package __init__ — that was true before too).
from sbr_tpu.obs.mem import fmt_bytes as _fmt_bytes, tile_peak as _tile_peak  # noqa: E402


def _table(headers, rows) -> str:
    widths = [len(h) for h in headers]
    rows = [[str(c) for c in r] for r in rows]
    for r in rows:
        widths = [max(w, len(c)) for w, c in zip(widths, r)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*r) for r in rows]
    return "\n".join(lines)


def _jit_by_name(events) -> dict:
    """Aggregate jit_call events by name."""
    agg: dict = {}
    for ev in events:
        if ev.get("kind") != "jit_call":
            continue
        a = agg.setdefault(
            ev.get("name", "?"),
            {"calls": 0, "trace_s": 0.0, "compile_s": 0.0, "execute_s": 0.0, "flops": 0.0},
        )
        a["calls"] += 1
        for k in ("trace_s", "compile_s", "execute_s"):
            a[k] += float(ev.get(k, 0.0))
        if ev.get("flops") is not None:
            # accumulate per event: one name can cover several compiled
            # shapes, so a single per-call flops value is not representative
            a["flops"] += float(ev["flops"])
    return agg


def _status_by_stage(events) -> dict:
    out: dict = {}
    for ev in events:
        if ev.get("kind") == "status":
            out[ev.get("stage", "?")] = ev.get("counts", {})
    return out


def render(run: dict) -> str:
    m = run["manifest"]
    events = run["events"]
    out = []
    dev = m.get("device") or {}
    out.append(f"run      {run['dir']}")
    out.append(
        f"label    {m.get('label')}   status {m.get('status')}   "
        f"started {m.get('started_at')}   duration {_fmt_s(m.get('duration_s'))}"
    )
    out.append(
        f"device   {dev.get('device_kind', '?')} ({dev.get('platform', '?')} ×"
        f"{dev.get('device_count', '?')})   jax {dev.get('jax_version', '?')}"
    )
    mem = m.get("memory") or {}
    out.append(
        f"memory   peak live buffers {_fmt_bytes(mem.get('peak_live_buffer_bytes'))}"
        + (
            f"   device peak {_fmt_bytes(mem.get('peak_device_bytes'))}"
            if mem.get("peak_device_bytes")
            else ""
        )
        + (f"   peak span {mem['peak_span']}" if mem.get("peak_span") else "")
        + ("   (details: report memory RUN_DIR)" if mem.get("tiles") or mem.get("plan") else "")
    )
    out.append(f"events   {m.get('n_events')}{_bad_lines_note(run)}")

    stages = m.get("stages") or {}
    if stages:
        total = sum(v["total_s"] for v in stages.values()) or 1.0
        out += ["", "STAGES"]
        out.append(
            _table(
                ["stage", "count", "total", "share"],
                [
                    [k, v["count"], _fmt_s(v["total_s"]), f"{100 * v['total_s'] / total:.1f}%"]
                    for k, v in stages.items()
                ],
            )
        )

    jit = _jit_by_name(events)
    if jit:
        out += ["", "JIT (compile vs execute)"]
        rows = []
        for name, a in sorted(jit.items()):
            rate = ""
            if a["flops"] and a["execute_s"]:
                rate = f"{a['flops'] / a['execute_s'] / 1e9:.2f} GFLOP/s"
            rows.append(
                [name, a["calls"], _fmt_s(a["trace_s"]), _fmt_s(a["compile_s"]), _fmt_s(a["execute_s"]), rate]
            )
        out.append(_table(["program", "calls", "trace", "compile", "execute", "throughput"], rows))
        j = m.get("jit") or {}
        out.append(
            f"totals: {j.get('calls', 0)} calls ({j.get('cache_hits', 0)} cache hits), "
            f"compile {_fmt_s(j.get('compile_s'))}, execute {_fmt_s(j.get('execute_s'))}"
        )

    status = _status_by_stage(events)
    if status:
        out += ["", "STATUS GRIDS"]
        rows = [
            [stage, ", ".join(f"{k}={v}" for k, v in counts.items() if v)]
            for stage, counts in status.items()
        ]
        out.append(_table(["stage", "counts"], rows))

    health = m.get("health") or {}
    if health:
        worst = sum(v.get("divergent", 0) for v in health.values())
        out += ["", f"HEALTH ({'DIVERGENT' if worst else 'ok'})"]
        out.append(
            _table(
                ["stage", "cells", "divergent", "max residual"],
                [
                    [k, v.get("cells", "-"), v.get("divergent", 0), _fmt_resid(v.get("max_residual"))]
                    for k, v in sorted(health.items())
                ],
            )
        )
        out.append("(details: python -m sbr_tpu.obs.report health RUN_DIR)")

    xla = m.get("xla") or {}
    if xla.get("compiles"):
        out += ["", "XLA COMPILES (jax.monitoring)"]
        out.append(
            f"{xla['compiles']} backend compile(s): "
            f"jaxpr trace {_fmt_s(xla.get('jaxpr_trace_s'))}, "
            f"mlir lowering {_fmt_s(xla.get('mlir_lowering_s'))}, "
            f"backend compile {_fmt_s(xla.get('backend_compile_s'))}"
        )
        by_span = xla.get("by_span") or {}
        if by_span:
            out.append(
                _table(
                    ["span", "compiles", "backend compile"],
                    [
                        [k, v.get("compiles", 0), _fmt_s(v.get("backend_compile_s"))]
                        for k, v in by_span.items()
                    ],
                )
            )
    elif xla and not xla.get("monitoring", True):
        out += ["", "XLA COMPILES: jax.monitoring unavailable on this jax build"]

    retraces = m.get("retraces") or {}
    if retraces:
        over = [k for k, v in retraces.items() if v.get("over_budget")]
        out += ["", f"RETRACES{' (OVER BUDGET: ' + ', '.join(over) + ')' if over else ''}"]
        out.append(
            _table(
                ["program", "traces", "budget", "over budget"],
                [
                    [k, v.get("traces"), v.get("budget"), "YES" if v.get("over_budget") else "-"]
                    for k, v in retraces.items()
                ],
            )
        )

    profiles = m.get("profiles") or []
    if profiles:
        out += ["", "PROFILER CAPTURES"]
        out.append(
            _table(
                ["label", "files", "size", "window", "trace dir"],
                [
                    [
                        p.get("label"),
                        p.get("files"),
                        ("pruned" if p.get("pruned") else _fmt_bytes(p.get("bytes"))),
                        _fmt_s(p.get("window_s")),
                        p.get("trace_dir"),
                    ]
                    for p in profiles
                ],
            )
        )

    mx = m.get("metrics") or {}
    if mx.get("counters") or mx.get("timers") or mx.get("gauges"):
        out += ["", "METRICS"]
        rows = [["counter", k, v] for k, v in (mx.get("counters") or {}).items()]
        rows += [["gauge", k, v] for k, v in (mx.get("gauges") or {}).items()]
        rows += [
            ["timer", k, f"n={h['count']} total={_fmt_s(h['total_s'])} p50={_fmt_s(h['p50_s'])}"]
            for k, h in (mx.get("timers") or {}).items()
        ]
        out.append(_table(["type", "name", "value"], rows))

    return "\n".join(out)


def _fmt_resid(v) -> str:
    return "-" if v is None else f"{float(v):.2e}"


def _health_by_stage(events) -> dict:
    """Fold `health` events per stage: summed cells/divergent/flag counts,
    max residual, last worst-cells table and residual histogram."""
    out: dict = {}
    for ev in events:
        if ev.get("kind") != "health":
            continue
        stage = ev.get("stage", "?")
        agg = out.setdefault(
            stage,
            {
                "events": 0,
                "cells": 0,
                "divergent": 0,
                "max_residual": None,
                "flag_counts": {},
                "worst_cells": [],
                "residual_hist": {},
                "iterations_total": 0,
                "iterations_max": 0,
            },
        )
        agg["events"] += 1
        agg["cells"] += int(ev.get("cells", 0))
        agg["divergent"] += int(ev.get("divergent", 0))
        agg["iterations_total"] += int(ev.get("iterations_total", 0))
        agg["iterations_max"] = max(agg["iterations_max"], int(ev.get("iterations_max", 0)))
        mr = ev.get("max_residual")
        if mr is not None:
            prev = agg["max_residual"]
            agg["max_residual"] = mr if prev is None else max(prev, mr)
        for name, n in (ev.get("flag_counts") or {}).items():
            agg["flag_counts"][name] = agg["flag_counts"].get(name, 0) + int(n)
        if ev.get("worst_cells"):
            agg["worst_cells"] = ev["worst_cells"]
        if ev.get("residual_hist"):
            agg["residual_hist"] = ev["residual_hist"]
    return out


def _ascii_hist(hist: dict, width: int = 40) -> list:
    """Render a {bucket_label: count} histogram as aligned ASCII bars."""
    if not hist:
        return []
    peak = max(hist.values()) or 1
    label_w = max(len(k) for k in hist)
    lines = []
    for label, count in hist.items():
        bar = "#" * max(1, round(width * count / peak)) if count else ""
        lines.append(f"  {label:>{label_w}}  {count:>8}  {bar}")
    return lines


def render_health(run: dict) -> tuple:
    """Numerical-health report; returns (text, exit_code). Exit codes:
    0 healthy, 1 divergence detected, 3 no health data recorded (a run
    that was supposed to carry diagnostics but emitted none must not pass
    a CI gate silently)."""
    events = run["events"]
    stages = _health_by_stage(events)
    out = [f"run      {run['dir']}{_bad_lines_note(run)}"]
    if not stages:
        out.append("no health events recorded — was the run produced by an "
                    "instrumented solver/sweep with telemetry on?")
        return "\n".join(out), 3

    total_divergent = sum(v["divergent"] for v in stages.values())
    total_cells = sum(v["cells"] for v in stages.values())
    out.append(
        f"health   {'DIVERGENCE DETECTED' if total_divergent else 'OK'}: "
        f"{total_divergent}/{total_cells} divergent cells across {len(stages)} stage(s)"
    )

    out += ["", "STAGES"]
    rows = []
    for name, v in sorted(stages.items()):
        flags = ", ".join(f"{k}={n}" for k, n in sorted(v["flag_counts"].items())) or "-"
        # effective iterations (adaptive numerics, ISSUE 9): mean/max of
        # what cells ACTUALLY ran — under numerics="fixed" this just echoes
        # the constant budget
        iters = (
            f"{v['iterations_total'] / v['cells']:.1f}/{v['iterations_max']}"
            if v["cells"]
            else "-"
        )
        rows.append(
            [name, v["cells"], v["divergent"], _fmt_resid(v["max_residual"]), iters, flags]
        )
    out.append(
        _table(["stage", "cells", "divergent", "max resid", "eff iters μ/max", "flags"], rows)
    )

    # Per-scenario census (ISSUE 14): health events tagged by the composed
    # scenario engine carry ``scenario`` (and ``bank`` for multi-bank
    # contagion). The stage fold above already keeps them separate — the
    # tags suffix the stage key — but this roll-up answers the operator
    # question directly: which SCENARIO is divergent, across however many
    # banks/stages it spanned, instead of one census mixing all banks.
    scen_agg: dict = {}
    for ev in events:
        if ev.get("kind") != "health" or "scenario" not in ev:
            continue
        agg = scen_agg.setdefault(
            str(ev["scenario"]),
            {"events": 0, "cells": 0, "divergent": 0, "banks": set()},
        )
        agg["events"] += 1
        agg["cells"] += int(ev.get("cells", 0))
        agg["divergent"] += int(ev.get("divergent", 0))
        if "bank" in ev:
            agg["banks"].add(int(ev["bank"]))
    if scen_agg:
        out += ["", "SCENARIOS"]
        out.append(
            _table(
                ["scenario", "events", "cells", "divergent", "banks"],
                [
                    [name, v["events"], v["cells"], v["divergent"],
                     len(v["banks"]) or "-"]
                    for name, v in sorted(scen_agg.items())
                ],
            )
        )

    # NaN census: the poison-tracking subset of the flag counts.
    nan_rows = []
    for name, v in sorted(stages.items()):
        fc = v["flag_counts"]
        nan_in, nan_out, nonf = (
            fc.get("nan_input", 0), fc.get("nan_output", 0), fc.get("nonfinite_residual", 0),
        )
        if nan_in or nan_out or nonf:
            nan_rows.append([name, nan_in, nan_out, nonf])
    if nan_rows:
        out += ["", "NaN CENSUS"]
        out.append(_table(["stage", "nan_input", "nan_output", "nonfinite_residual"], nan_rows))

    for name, v in sorted(stages.items()):
        if v["worst_cells"]:
            out += ["", f"WORST CELLS — {name}"]
            out.append(
                _table(
                    ["index", "residual", "status", "flags"],
                    [
                        [
                            ",".join(str(i) for i in c.get("index", [])),
                            _fmt_resid(c.get("residual")),
                            c.get("status", "-"),
                            ",".join(c.get("flags", [])) or "-",
                        ]
                        for c in v["worst_cells"]
                    ],
                )
            )
        if v["residual_hist"]:
            out += ["", f"RESIDUAL HISTOGRAM — {name} (|f(x*)| by decade)"]
            out += _ascii_hist(v["residual_hist"])

    return "\n".join(out), 1 if total_divergent else 0


def _resilience_by_kind(events) -> dict:
    """Fold fault/retry/repair events (the `sbr_tpu.resilience` emissions)
    from the event log — the source of truth even when a kill -9 meant the
    manifest roll-up was never finalized."""
    faults: dict = {}
    retries: dict = {}
    repairs: dict = {}
    failed_repairs = []
    for ev in events:
        kind = ev.get("kind")
        if kind == "fault":
            key = f"{ev.get('point', '?')}:{ev.get('fault', '?')}"
            faults[key] = faults.get(key, 0) + 1
        elif kind == "retry":
            agg = retries.setdefault(
                ev.get("scope", "?"), {"attempts": 0, "recovered": 0, "gave_up": 0}
            )
            agg["attempts"] = max(agg["attempts"], int(ev.get("attempt", 0)))
            outcome = ev.get("outcome")
            if outcome == "recovered":
                agg["recovered"] += 1
            elif outcome in ("gave_up", "budget_exhausted"):
                agg["gave_up"] += 1
        elif kind == "repair":
            agg = repairs.setdefault(ev.get("action", "?"), {"count": 0, "failed": 0})
            agg["count"] += 1
            if not ev.get("ok", True):
                agg["failed"] += 1
                failed_repairs.append(ev.get("target", "?"))
    return {
        "faults": faults,
        "retries": retries,
        "repairs": repairs,
        "failed_repairs": failed_repairs,
    }


def _resilience_gate(folded: dict) -> tuple:
    """(unrecovered_count, exit_code): nonzero exit whenever a retry scope
    gave up or a repair failed. A manifest status of "interrupted" is
    reported but NOT gated — a graceful preemption is recorded evidence,
    not an unrecovered failure (the resumed run completes elsewhere)."""
    unrecovered = sum(v["gave_up"] for v in folded["retries"].values()) + sum(
        v["failed"] for v in folded["repairs"].values()
    )
    return unrecovered, 1 if unrecovered else 0


def render_resilience(run: dict) -> tuple:
    """Fault/retry/repair report; returns (text, exit_code). Unlike
    `health` (exit 3 when diagnostics never flowed), an empty resilience
    log is a CLEAN run — nothing failed — and exits 0."""
    folded = _resilience_by_kind(run["events"])
    status = run["manifest"].get("status")
    unrecovered, code = _resilience_gate(folded)
    out = [f"run      {run['dir']}{_bad_lines_note(run)}"]
    out.append(f"status   {status}" + ("   (graceful preemption)" if status == "interrupted" else ""))
    if not any((folded["faults"], folded["retries"], folded["repairs"])):
        out.append("resilience  clean: no fault, retry, or repair events recorded")
        return "\n".join(out), code
    out.append(
        f"resilience  {'UNRECOVERED FAILURES: ' + str(unrecovered) if unrecovered else 'recovered'}: "
        f"{sum(folded['faults'].values())} fault(s) injected, "
        f"{len(folded['retries'])} retried scope(s), "
        f"{sum(v['count'] for v in folded['repairs'].values())} repair action(s)"
    )
    if folded["faults"]:
        out += ["", "INJECTED FAULTS"]
        out.append(
            _table(
                ["point:kind", "count"],
                [[k, v] for k, v in sorted(folded["faults"].items())],
            )
        )
    if folded["retries"]:
        out += ["", "RETRIES"]
        out.append(
            _table(
                ["scope", "max attempt", "recovered", "gave up"],
                [
                    [k, v["attempts"], v["recovered"], v["gave_up"] or "-"]
                    for k, v in sorted(folded["retries"].items())
                ],
            )
        )
    if folded["repairs"]:
        out += ["", "REPAIRS"]
        out.append(
            _table(
                ["action", "count", "failed"],
                [
                    [k, v["count"], v["failed"] or "-"]
                    for k, v in sorted(folded["repairs"].items())
                ],
            )
        )
        for target in folded["failed_repairs"]:
            out.append(f"  FAILED: {target}")
    return "\n".join(out), code


def resilience_json(run: dict) -> tuple:
    """Machine-readable equivalent of `render_resilience` (--json)."""
    folded = _resilience_by_kind(run["events"])
    unrecovered, code = _resilience_gate(folded)
    return {
        "dir": run["dir"],
        "status": run["manifest"].get("status"),
        **folded,
        "unrecovered": unrecovered,
        "bad_event_lines": run.get("bad_event_lines", 0),
        "exit": code,
    }, code


def render_json(run: dict) -> dict:
    """Machine-readable equivalent of `render` (--json): the manifest plus
    the per-name jit aggregation and per-stage status counts from events."""
    return {
        "dir": run["dir"],
        "manifest": run["manifest"],
        "jit_by_name": _jit_by_name(run["events"]),
        "status_by_stage": _status_by_stage(run["events"]),
        "bad_event_lines": run.get("bad_event_lines", 0),
    }


def health_json(run: dict) -> tuple:
    """Machine-readable equivalent of `render_health` (--json); returns
    (doc, exit_code) with the same exit-code contract."""
    stages = _health_by_stage(run["events"])
    bad = run.get("bad_event_lines", 0)
    if not stages:
        return {"dir": run["dir"], "stages": {}, "bad_event_lines": bad, "exit": 3}, 3
    total_divergent = sum(v["divergent"] for v in stages.values())
    code = 1 if total_divergent else 0
    return {
        "dir": run["dir"],
        "stages": stages,
        "total_cells": sum(v["cells"] for v in stages.values()),
        "total_divergent": total_divergent,
        "bad_event_lines": bad,
        "exit": code,
    }, code


def diff_json(a: dict, b: dict) -> dict:
    """Machine-readable equivalent of `diff` (--json)."""
    ma, mb = a["manifest"], b["manifest"]
    ja, jb = ma.get("jit") or {}, mb.get("jit") or {}
    sa, sb = ma.get("stages") or {}, mb.get("stages") or {}
    stages = {}
    for n in sorted(set(sa) | set(sb)):
        ta = sa.get(n, {}).get("total_s")
        tb = sb.get(n, {}).get("total_s")
        stages[n] = {
            "a_s": ta,
            "b_s": tb,
            "ratio": (tb / ta) if (ta and tb is not None) else None,
        }
    return {
        "a": a["dir"],
        "b": b["dir"],
        "duration": {"a_s": ma.get("duration_s"), "b_s": mb.get("duration_s")},
        "compile": {"a_s": ja.get("compile_s"), "b_s": jb.get("compile_s")},
        "execute": {"a_s": ja.get("execute_s"), "b_s": jb.get("execute_s")},
        "stages": stages,
    }


def diff(a: dict, b: dict) -> str:
    """Stage/jit-level diff of two runs (b relative to a)."""
    ma, mb = a["manifest"], b["manifest"]
    out = [f"A: {a['dir']}", f"B: {b['dir']}", ""]
    out.append(
        f"duration  A {_fmt_s(ma.get('duration_s'))}   B {_fmt_s(mb.get('duration_s'))}"
    )
    ja, jb = ma.get("jit") or {}, mb.get("jit") or {}
    out.append(
        f"compile   A {_fmt_s(ja.get('compile_s'))}   B {_fmt_s(jb.get('compile_s'))}"
    )
    out.append(
        f"execute   A {_fmt_s(ja.get('execute_s'))}   B {_fmt_s(jb.get('execute_s'))}"
    )
    sa, sb = ma.get("stages") or {}, mb.get("stages") or {}
    names = sorted(set(sa) | set(sb))
    if names:
        rows = []
        for n in names:
            ta = sa.get(n, {}).get("total_s")
            tb = sb.get(n, {}).get("total_s")
            if ta is not None and tb is not None:
                # presence, not truthiness: a sub-µs span rounds to 0.0 in
                # the manifest but is still in both runs
                ratio = f"{tb / ta:.2f}x" if ta else "-"
            else:
                ratio = "only A" if ta is not None else ("only B" if tb is not None else "-")
            rows.append([n, _fmt_s(ta), _fmt_s(tb), ratio])
        out += ["", "STAGES (B vs A)", _table(["stage", "A", "B", "B/A"], rows)]
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Elastic report (`elastic` subcommand — the scheduler/cache renderer/gate)
# ---------------------------------------------------------------------------


def _elastic_fold(events) -> dict:
    """Fold ``scheduler`` + ``cache`` events (the `resilience.elastic`
    emissions): per-host membership/throughput, scheduler action counts,
    tile counts by source, and cache outcome counts. The event log is the
    source of truth even when a kill -9 meant the manifest roll-up was
    never finalized (same contract as the resilience report)."""
    hosts: dict = {}
    scheduler: dict = {}
    cache: dict = {}
    tiles: dict = {}
    for ev in events:
        kind = ev.get("kind")
        if kind == "scheduler":
            action = ev.get("action", "?")
            scheduler[action] = scheduler.get(action, 0) + 1
            host = ev.get("host")
            if host:
                h = hosts.setdefault(
                    host,
                    {"tiles_done": 0, "computed": 0, "cached": 0,
                     "compute_s": 0.0, "compute_cells": 0,
                     "joined": False, "left": False, "reclaims": 0},
                )
                if action == "join":
                    h["joined"] = True
                elif action == "leave":
                    h["left"] = True
                elif action == "reclaim":
                    h["reclaims"] += 1
                elif action == "done":
                    h["tiles_done"] += 1
                    source = str(ev.get("source", "?"))
                    tiles[source] = tiles.get(source, 0) + 1
                    if source == "computed":
                        h["computed"] += 1
                        h["compute_s"] += float(ev.get("dur_s", 0.0))
                        h["compute_cells"] += int(ev.get("cells", 0))
                    else:
                        h["cached"] += 1
        elif kind == "cache":
            action = ev.get("action", "?")
            cache[action] = cache.get(action, 0) + 1
    for h in hosts.values():
        h["cells_per_sec"] = (
            round(h["compute_cells"] / h["compute_s"], 2) if h["compute_s"] > 0 else None
        )
    return {"hosts": hosts, "scheduler": scheduler, "cache": cache, "tiles": tiles}


def elastic_doc(run: dict) -> tuple:
    """Machine-readable elastic-scheduler report; returns (doc, exit_code).
    Exit 0 when scheduler events were recorded, 3 when the run carries no
    elastic data at all (a churn gate with nothing to read must not pass
    silently) — there is no failure exit here: unrecovered failures gate
    via ``report resilience``; this report is the membership/cache census
    CI asserts counts against (e.g. warm re-sweep ⇒ tiles.computed == 0)."""
    folded = _elastic_fold(run["events"])
    manifest_blk = run["manifest"].get("elastic") or {}
    # Scheduler events (or their manifest roll-up) are the signal that the
    # run WAS elastic — a cache-only block (plain run_tiled_grid with
    # SBR_TILE_CACHE_DIR) must not satisfy a churn gate's exit-0 check.
    code = 3 if not folded["scheduler"] and not manifest_blk.get("scheduler") else 0
    doc = {
        "dir": run["dir"],
        **folded,
        "manifest": manifest_blk or None,
        "tiles_computed": folded["tiles"].get("computed", 0),
        "tiles_from_cache": folded["tiles"].get("cache", 0)
        + folded["tiles"].get("local", 0),
        "bad_event_lines": run.get("bad_event_lines", 0),
        "exit": code,
    }
    return doc, code


def render_elastic(run: dict) -> tuple:
    """Human-readable elastic report; same exit contract as `elastic_doc`."""
    doc, code = elastic_doc(run)
    out = [f"run      {run['dir']}{_bad_lines_note(run)}"]
    if code == 3:
        out.append(
            "no scheduler events recorded — was the sweep run through the "
            "elastic scheduler (run_tiled_grid_multihost, SBR_ELASTIC unset/1)?"
        )
        return "\n".join(out), code
    tiles = doc["tiles"]
    out.append(
        "elastic  "
        + ", ".join(f"{tiles.get(k, 0)} {k}" for k in ("computed", "cache", "local"))
        + f" tile(s) across {len(doc['hosts'])} host(s)"
    )
    if doc["hosts"]:
        out += ["", "HOSTS"]
        out.append(
            _table(
                ["host", "tiles", "computed", "cached", "cells/s", "reclaims", "join", "leave"],
                [
                    [
                        h,
                        v["tiles_done"],
                        v["computed"],
                        v["cached"],
                        v["cells_per_sec"] if v["cells_per_sec"] is not None else "-",
                        v["reclaims"] or "-",
                        "yes" if v["joined"] else "-",
                        "yes" if v["left"] else "-",
                    ]
                    for h, v in sorted(doc["hosts"].items())
                ],
            )
        )
    if doc["scheduler"]:
        out += ["", "SCHEDULER EVENTS"]
        out.append(
            _table(
                ["action", "count"],
                [[k, v] for k, v in sorted(doc["scheduler"].items())],
            )
        )
    if doc["cache"]:
        out += ["", "GLOBAL TILE CACHE"]
        out.append(
            _table(
                ["outcome", "count"],
                [[k, v] for k, v in sorted(doc["cache"].items())],
            )
        )
    return "\n".join(out), code


def _main_elastic(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report elastic",
        description="Elastic-scheduler report for one run (hosts, claims, "
        "tile sources, global-cache outcomes); exit 3 when no scheduler "
        "events were recorded",
    )
    parser.add_argument("run_dir", help="run directory (contains manifest.json)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    try:
        run = load_run(args.run_dir)
    except (FileNotFoundError, json.JSONDecodeError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    if args.json:
        doc, code = elastic_doc(run)
        print(json.dumps(doc, default=str))
        return code
    text, code = render_elastic(run)
    print(text)
    return code


# ---------------------------------------------------------------------------
# Fleet report (`fleet` subcommand — the serving-fleet renderer/gate)
# ---------------------------------------------------------------------------


def fleet_doc(run_dir, stuck_after_s: float = 600.0) -> tuple:
    """Machine-readable serving-fleet report from a ROUTER run dir: the
    rolling ``fleet.json`` snapshot (`sbr_tpu.serve.router`, atomic
    rename — readable mid-flight) plus the obs ``fleet`` event fold.
    Returns (doc, exit_code).

    Exit codes: 0 healthy; 1 on LOST queries (a client got a non-200,
    non-429 answer — failover exists precisely so this never happens) or
    a breaker STUCK open (state "open" in the final snapshot for longer
    than ``stuck_after_s`` — a breaker parked over a dead worker clears
    when the heartbeat TTL reaps the worker from the table, so keep
    ``stuck_after_s`` at or above the fleet's heartbeat TTL); 2 when
    ``run_dir`` is not a directory; 3 when no fleet data was recorded
    (a fleet gate with nothing to read must not pass silently)."""
    run_dir = Path(run_dir)
    if not run_dir.is_dir():
        return {"dir": str(run_dir), "error": "not a directory", "exit": 2}, 2
    snapshot = None
    try:
        snapshot = json.loads((run_dir / "fleet.json").read_text())
    except (OSError, json.JSONDecodeError):
        pass
    events_fold: dict = {}
    bad_lines = 0
    try:
        run = load_run(run_dir)
        bad_lines = run.get("bad_event_lines", 0)
        for ev in run["events"]:
            if ev.get("kind") == "fleet":
                action = str(ev.get("action", "?"))
                events_fold[action] = events_fold.get(action, 0) + 1
        manifest_fleet = run["manifest"].get("fleet") or {}
    except (FileNotFoundError, json.JSONDecodeError):
        manifest_fleet = {}
    if snapshot is None and not events_fold and not manifest_fleet:
        return {
            "dir": str(run_dir),
            "error": "no fleet data (no fleet.json, no fleet events)",
            "exit": 3,
        }, 3

    counters = (snapshot or {}).get("counters") or {}
    workers = (snapshot or {}).get("workers") or {}
    # The event fold is the kill -9 fallback (a router that died before
    # its throttled fleet.json caught up): take the max of the two views
    # for EVERY gated/asserted count, never the sum — and never trust the
    # snapshot alone, since Router initializes every counter key (a plain
    # dict.get fallback would always pick the stale snapshot zero).
    def _best(counter_key: str, event_key: str) -> int:
        return max(int(counters.get(counter_key, 0)),
                   int(events_fold.get(event_key, 0)))

    lost = _best("failed", "lost")
    stuck = sorted(
        h
        for h, w in workers.items()
        if w.get("breaker") == "open"
        and isinstance(w.get("breaker_age_s"), (int, float))
        and w["breaker_age_s"] > stuck_after_s
    )
    breaches = []
    if lost > 0:
        breaches.append(f"{lost} lost quer(ies) — failover failed to absorb")
    if stuck:
        breaches.append(
            f"breaker stuck open > {stuck_after_s:g}s for: {', '.join(stuck)}"
        )
    code = 1 if breaches else 0
    doc = {
        "dir": str(run_dir),
        "snapshot": snapshot,
        "counters": counters,
        "workers": workers,
        "events": events_fold,
        "manifest_fleet": manifest_fleet or None,
        "lost": lost,
        "failover_count": _best("failover", "failover"),
        "shed": _best("shed", "shed"),
        "degraded": _best("degraded", "degraded"),
        "stuck_breakers": stuck,
        "stuck_after_s": stuck_after_s,
        "breaches": breaches,
        "bad_event_lines": bad_lines,
        "exit": code,
    }
    return doc, code


def render_fleet(doc: dict) -> str:
    """Human-readable fleet report; same exit contract as `fleet_doc`."""
    out = [f"run      {doc['dir']}"]
    if doc["exit"] in (2, 3):
        out.append(doc.get("error", "no fleet data"))
        if doc["exit"] == 3:
            out.append(
                "was the run produced by sbr_tpu.serve.router (it writes a "
                "rolling fleet.json + fleet events)?"
            )
        return "\n".join(out)
    snap = doc.get("snapshot") or {}
    c = doc["counters"]
    out.append(
        f"fleet    {int(c.get('queries', 0))} quer(ies): "
        f"{int(c.get('completed', 0))} completed, {doc['lost']} lost, "
        f"{doc['shed']} shed, {doc['degraded']} degraded"
    )
    out.append(
        f"routing  {doc['failover_count']} failover(s), "
        f"{int(c.get('hedged', 0))} hedge(s) ({int(c.get('hedge_wins', 0))} won), "
        f"{int(c.get('forward_errors', 0))} forward error(s)"
    )
    lat = snap.get("latency_ms") or {}
    if lat.get("count"):
        out.append(
            f"latency  p50 {_fmt_val_ms(lat.get('p50'))}   "
            f"p95 {_fmt_val_ms(lat.get('p95'))}   p99 {_fmt_val_ms(lat.get('p99'))}"
        )
    if doc["workers"]:
        out += ["", "WORKERS"]
        out.append(
            _table(
                ["worker", "breaker", "age s", "forwards", "failures",
                 "ewma ms", "healthz"],
                [
                    [
                        h,
                        (w.get("breaker") or "-").upper()
                        if h in doc["stuck_breakers"] else (w.get("breaker") or "-"),
                        "-" if w.get("breaker_age_s") is None else f"{w['breaker_age_s']:g}",
                        w.get("forwards", 0),
                        w.get("failures", 0),
                        w.get("ewma_ms", "-"),
                        w.get("healthz") or "-",
                    ]
                    for h, w in sorted(doc["workers"].items())
                ],
            )
        )
    if doc["events"]:
        out += ["", "FLEET EVENTS"]
        out.append(
            _table(
                ["action", "count"],
                [[k, v] for k, v in sorted(doc["events"].items())],
            )
        )
    out.append("")
    if doc["breaches"]:
        out.append("GATE: FLEET BREACH")
        for b in doc["breaches"]:
            out.append(f"  {b}")
    else:
        out.append("GATE: ok (zero lost queries, no breaker stuck open)")
    return "\n".join(out)


def _fmt_val_ms(v) -> str:
    return "-" if v is None else f"{v:.2f} ms"


def _main_fleet(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report fleet",
        description="Serving-fleet report for one router run (rolling "
        "fleet.json + fleet events); exit 1 on lost queries or a breaker "
        "stuck open, 3 when no fleet data was recorded",
    )
    parser.add_argument("run_dir", help="router run directory (contains fleet.json)")
    parser.add_argument(
        "--stuck-after-s", type=float, default=600.0, dest="stuck_after_s",
        help="age (s) past which an open breaker counts as stuck (default "
        "600; keep >= the fleet heartbeat TTL so dead workers are reaped "
        "from the table before their breakers can read as stuck)",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = fleet_doc(args.run_dir, args.stuck_after_s)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_fleet(doc))
    return code


# ---------------------------------------------------------------------------
# Numerics-audit report (`audit` subcommand — ISSUE 17 drift gate)
# ---------------------------------------------------------------------------


def audit_doc(run_dir) -> tuple:
    """Machine-readable numerics-audit report (`sbr_tpu.obs.audit`): the
    manifest ``audit`` roll-up plus the per-event fold of canary probe
    verdicts and cycle summaries. Returns (doc, exit_code).

    Exit codes: 0 every probe passed; 1 on ANY drift verdict (probe event,
    cycle roll-up, manifest tally, or a scheduler ``error`` event — an
    audit that crashed mid-battery must not read as clean) — the manifest
    tally and the event fold are merged max-style, never summed, so a run
    killed before its manifest flushed still gates on its events; 3 when
    the run recorded no audit data at all (a drift gate with nothing to
    read must not pass silently); 2 when ``run_dir`` is not a directory."""
    run_dir = Path(run_dir)
    if not run_dir.is_dir():
        return {"dir": str(run_dir), "error": "not a directory", "exit": 2}, 2
    try:
        run = load_run(run_dir)
    except (FileNotFoundError, json.JSONDecodeError) as err:
        return {"dir": str(run_dir), "error": str(err), "exit": 2}, 2
    manifest_blk = run["manifest"].get("audit") or {}
    events = [ev for ev in run["events"] if ev.get("kind") == "audit"]
    if not manifest_blk and not events:
        return {
            "dir": str(run_dir),
            "error": "no audit data (no audit events, no manifest roll-up)",
            "exit": 3,
        }, 3
    # Per-probe LAST verdict (later events supersede: a probe that drifted
    # once and then went green after --update-goldens reads as its final
    # state; the drift still counts in the drift tally below).
    probes: dict = {}
    drift_events = 0
    pass_events = 0
    errors = 0
    cycles: list = []
    for ev in events:
        action = str(ev.get("action", "?"))
        if action == "probe":
            name = str(ev.get("probe", "?"))
            verdict = str(ev.get("verdict", "?"))
            ent = probes.setdefault(name, {"events": 0})
            ent["events"] += 1
            ent["verdict"] = verdict
            ent["tier"] = ev.get("tier")
            ent["detail"] = ev.get("detail")
            ent["duration_ms"] = ev.get("duration_ms")
            if ev.get("cycle") is not None:
                ent["cycle"] = ev.get("cycle")
            if verdict == "drift":
                drift_events += 1
                ent["drift_cycle"] = ev.get("cycle")
            elif verdict == "pass":
                pass_events += 1
        elif action == "cycle":
            cycles.append({
                "cycle": ev.get("cycle"),
                "verdict": ev.get("verdict"),
                "probes": ev.get("probes"),
                "drift": ev.get("drift"),
                "missing": ev.get("missing"),
                "duration_s": ev.get("duration_s"),
                "key_hash": ev.get("key_hash"),
            })
        elif action == "error":
            errors += 1
    # Manifest tally vs event fold: max of the two views for every gated
    # count (the fleet_doc rule — a worker killed before its manifest
    # flushed still has its events; a torn events.jsonl still has the
    # manifest), never the sum.
    drift = max(drift_events, int(manifest_blk.get("drift", 0)))
    passed = max(pass_events, int(manifest_blk.get("passed", 0)))
    errors = max(errors, int(manifest_blk.get("error", 0)))
    drifted = sorted(
        n for n, e in probes.items() if e.get("verdict") == "drift"
    )
    last_cycle = manifest_blk.get("last_cycle")
    last_verdict = manifest_blk.get("last_verdict")
    if cycles:
        last_cycle = cycles[-1].get("cycle", last_cycle)
        last_verdict = cycles[-1].get("verdict", last_verdict)
    breaches = []
    if drift > 0:
        who = f" ({', '.join(drifted)})" if drifted else ""
        breaches.append(f"{drift} drift verdict(s){who}")
    if last_verdict == "drift" and not breaches:
        breaches.append("last cycle verdict is drift")
    if errors > 0:
        breaches.append(f"{errors} audit error event(s) — battery crashed")
    code = 1 if breaches else 0
    doc = {
        "dir": str(run_dir),
        "manifest_audit": manifest_blk or None,
        "probes": probes,
        "cycles": cycles,
        "drift": drift,
        "passed": passed,
        "errors": errors,
        "drifted_probes": drifted,
        "last_cycle": last_cycle,
        "last_verdict": last_verdict,
        "breaches": breaches,
        "bad_event_lines": run.get("bad_event_lines", 0),
        "exit": code,
    }
    return doc, code


def render_audit(doc: dict) -> str:
    """Human-readable audit report; same exit contract as `audit_doc`."""
    out = [f"run      {doc['dir']}"]
    if doc["exit"] in (2, 3):
        out.append(doc.get("error", "no audit data"))
        if doc["exit"] == 3:
            out.append(
                "was the battery run with obs enabled (python -m "
                "sbr_tpu.obs.audit --obs-dir DIR, or SBR_AUDIT=1 serving)?"
            )
        return "\n".join(out)
    out.append(
        f"audit    {doc['passed']} pass, {doc['drift']} drift, "
        f"{doc['errors']} error(s)"
        + (f"   last cycle {doc['last_cycle']} ({doc['last_verdict']})"
           if doc.get("last_cycle") is not None else "")
    )
    if doc.get("bad_event_lines"):
        out.append(f"warning  {doc['bad_event_lines']} torn event line(s) skipped")
    if doc["probes"]:
        out += ["", "PROBES"]
        out.append(
            _table(
                ["probe", "tier", "verdict", "runs", "last ms", "detail"],
                [
                    [
                        n,
                        e.get("tier") or "-",
                        str(e.get("verdict", "?")).upper()
                        if e.get("verdict") == "drift" else e.get("verdict", "?"),
                        e.get("events", 0),
                        "-" if e.get("duration_ms") is None
                        else f"{e['duration_ms']:.1f}",
                        (e.get("detail") or "-")[:60],
                    ]
                    for n, e in sorted(doc["probes"].items())
                ],
            )
        )
    if doc["cycles"]:
        out += ["", "CYCLES"]
        out.append(
            _table(
                ["cycle", "verdict", "probes", "drift", "missing", "s"],
                [
                    [
                        "-" if c.get("cycle") is None else c["cycle"],
                        c.get("verdict", "-"),
                        c.get("probes", "-"), c.get("drift", "-"),
                        c.get("missing", "-"),
                        "-" if c.get("duration_s") is None
                        else f"{c['duration_s']:.2f}",
                    ]
                    for c in doc["cycles"][-12:]
                ],
            )
        )
    out.append("")
    if doc["breaches"]:
        out.append("GATE: NUMERICS DRIFT")
        for b in doc["breaches"]:
            out.append(f"  {b}")
    else:
        out.append("GATE: ok (every audited probe matched its golden)")
    return "\n".join(out)


def _main_audit(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report audit",
        description="Numerics-audit report for one run (audit events + "
        "manifest roll-up from sbr_tpu.obs.audit canary batteries); exit 1 "
        "on any drift verdict, 3 when no audit data was recorded",
    )
    parser.add_argument("run_dir", help="obs run directory with audit events")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = audit_doc(args.run_dir)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_audit(doc))
    return code


# ---------------------------------------------------------------------------
# Workload-demand report (`demand` subcommand — ISSUE 18)
# ---------------------------------------------------------------------------


def demand_doc(run_dirs, floor=None, cache_dir=None) -> tuple:
    """Machine-readable workload-demand report (`sbr_tpu.obs.demand`):
    merges the lifetime demand surfaces from each run's rolling
    ``demand.json`` (worker and single-engine runs alike) into hot-region
    tables, top-k heavy-hitter fingerprints, warm/cold coverage ratios,
    and a freshly ranked advisor plan (against ``cache_dir``'s tile-cache
    cell index when given). Returns (doc, exit_code).

    Exit codes: 0 healthy; 1 when hot-region warm coverage is under the
    floor (``--floor`` or ``SBR_DEMAND_COVERAGE_FLOOR``; no floor = gate
    disarmed); 3 when no run recorded demand data (a coverage gate with
    nothing to read must not pass silently); 2 when some ``run_dir`` is
    not a directory."""
    from sbr_tpu.obs import demand as _demand

    if floor is None:
        floor = _demand.coverage_floor()
    surfaces, per_dir, bad = [], [], 0
    for d in run_dirs:
        d = Path(d)
        if not d.is_dir():
            return {"dir": str(d), "error": "not a directory", "exit": 2}, 2
        snap_path = d / "demand.json"
        if not snap_path.is_file():
            per_dir.append({"dir": str(d), "queries": 0, "demand_json": False})
            continue
        try:
            snap = json.loads(snap_path.read_text())
            surface = snap["totals"]
        except (OSError, ValueError, KeyError, TypeError):
            bad += 1
            per_dir.append({"dir": str(d), "queries": 0, "demand_json": False})
            continue
        surfaces.append(surface)
        per_dir.append({
            "dir": str(d),
            "queries": int(surface.get("queries") or 0),
            "demand_json": True,
        })
    merged = _demand.merge_surfaces(surfaces) if surfaces else None
    if merged is None or not merged.get("queries"):
        return {
            "dirs": [str(d) for d in run_dirs],
            "error": "no demand data (no demand.json with queries — was the "
            "run served with SBR_DEMAND=1?)",
            "bad_demand_files": bad,
            "exit": 3,
        }, 3
    hot = _demand.hot_bins(merged)
    hot_q = sum(h["count"] for h in hot)
    hot_warm = sum(h["warm"] for h in hot)
    hot_cov = round(hot_warm / hot_q, 4) if hot_q else 0.0
    coverage = _demand.coverage_from_cache_dir(cache_dir) if cache_dir else None
    plan = _demand.advisor_plan(merged, coverage, floor=floor)
    sketch = _demand.MisraGries.from_doc(merged.get("sketch") or {})
    top_fps = [
        {
            "fingerprint": item, "count": count,
            **({k: payload.get(k) for k in ("beta", "u", "scenario", "kind")}
               if isinstance(payload, dict) else {}),
        }
        for item, count, payload in sketch.top(_demand.topk())
    ]
    sources: dict = {}
    for cell in (merged.get("cells") or {}).values():
        for s, v in (cell.get("sources") or {}).items():
            sources[s] = sources.get(s, 0) + int(v)
    breaches = []
    if floor is not None and hot_cov < floor:
        breaches.append(
            f"hot-region warm coverage {hot_cov:.3f} under floor {floor:g}"
        )
    code = 1 if breaches else 0
    doc = {
        "dirs": [str(d) for d in run_dirs],
        "per_dir": per_dir,
        "queries": int(merged["queries"]),
        "bins": merged["bins"],
        "hot_bins": hot,
        "hot_warm_coverage": hot_cov,
        "floor": floor,
        "sources": {k: sources[k] for k in sorted(sources)},
        "top_fingerprints": top_fps,
        "advisor": plan,
        "cache_dir": str(cache_dir) if cache_dir else None,
        "bad_demand_files": bad,
        "breaches": breaches,
        "exit": code,
    }
    return doc, code


def render_demand(doc: dict) -> str:
    """Human-readable demand report; same exit contract as `demand_doc`."""
    if doc["exit"] == 2:
        return f"run      {doc['dir']}\n{doc.get('error', 'not a directory')}"
    if doc["exit"] == 3:
        out = [f"runs     {', '.join(doc['dirs'])}", doc.get("error", "no demand data")]
        return "\n".join(out)
    out = [f"runs     {', '.join(doc['dirs'])}"]
    out.append(
        f"demand   {doc['queries']} quer(ies) on a {doc['bins']}x{doc['bins']} "
        f"(beta, u) grid; hot region {len(doc['hot_bins'])} bin(s), "
        f"warm coverage {doc['hot_warm_coverage']:.3f}"
        + (f" (floor {doc['floor']:g})" if doc.get("floor") is not None else "")
    )
    if doc.get("bad_demand_files"):
        out.append(f"warning  {doc['bad_demand_files']} torn demand.json skipped")
    if doc["sources"]:
        out.append("sources  " + ", ".join(
            f"{k}={v}" for k, v in doc["sources"].items()
        ))
    if doc["hot_bins"]:
        out += ["", "HOT REGION (bins covering >= 50% of demand)"]
        out.append(_table(
            ["bin", "beta", "u", "count", "share", "warm", "coverage"],
            [
                [
                    h["bin"],
                    f"[{h['beta_lo']:g},{h['beta_hi']:g})",
                    f"[{h['u_lo']:g},{h['u_hi']:g})",
                    h["count"],
                    f"{h['share']:.2f}",
                    h["warm"],
                    f"{h['warm_coverage']:.2f}",
                ]
                for h in doc["hot_bins"]
            ],
        ))
    if doc["top_fingerprints"]:
        out += ["", "TOP FINGERPRINTS (Misra-Gries heavy hitters)"]
        out.append(_table(
            ["fingerprint", "count", "beta", "u", "scenario", "kind"],
            [
                [
                    f["fingerprint"],
                    f["count"],
                    "-" if f.get("beta") is None else f"{f['beta']:g}",
                    "-" if f.get("u") is None else f"{f['u']:g}",
                    f.get("scenario") or "-",
                    f.get("kind") or "-",
                ]
                for f in doc["top_fingerprints"][:12]
            ],
        ))
    plan = doc.get("advisor") or {}
    if plan.get("tiles"):
        out += ["", f"ADVISOR PLAN {plan.get('plan_fingerprint', '?')}"
                + (f" (cache {doc['cache_dir']})" if doc.get("cache_dir") else "")]
        out.append(_table(
            ["rank", "bin", "score", "count", "cells", "tile cov"],
            [
                [
                    t["rank"], t["bin"], f"{t['score']:g}", t["count"],
                    t["cells"], f"{t['tile_coverage']:.2f}",
                ]
                for t in plan["tiles"]
            ],
        ))
    out.append("")
    if doc["breaches"]:
        out.append("GATE: COLD HOT-REGION")
        for b in doc["breaches"]:
            out.append(f"  {b}")
    else:
        out.append("GATE: ok" + (
            " (hot-region warm coverage clears the floor)"
            if doc.get("floor") is not None else " (no coverage floor set)"
        ))
    return "\n".join(out)


def _main_demand(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report demand",
        description="Workload-demand report over one or more run dirs "
        "(rolling demand.json surfaces from sbr_tpu.obs.demand): hot-region "
        "tables, top-k heavy-hitter fingerprints, warm/cold coverage, and "
        "the ranked prefetch-advisor plan; exit 1 when hot-region warm "
        "coverage is under the floor, 3 when no demand data was recorded",
    )
    parser.add_argument("run_dirs", nargs="+",
                        help="obs run director(ies) with demand.json")
    parser.add_argument("--floor", type=float, default=None,
                        help="warm-coverage gate floor (default "
                        "SBR_DEMAND_COVERAGE_FLOOR; unset = gate disarmed)")
    parser.add_argument("--cache-dir", default=None, dest="cache_dir",
                        help="tile-cache root (SBR_TILE_CACHE_DIR) whose "
                        "cell index feeds the advisor's coverage input")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = demand_doc(args.run_dirs, floor=args.floor,
                           cache_dir=args.cache_dir)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_demand(doc))
    return code


def prewarm_doc(run_dir) -> tuple:
    """Machine-readable prefetch-controller report
    (`sbr_tpu.serve.prewarm`): folds the run's ``prewarm`` events (with
    the manifest roll-up as fallback for a torn event log) into per-plan
    progress, tile sources, abandonment by reason, and the final warm
    verdict of every completed plan. Returns (doc, exit_code).

    Exit codes: 0 healthy; 1 when tiles were abandoned over budget or a
    plan completed COLD (``plan_done`` with warm < tiles — the sweep ran
    but the hot region still can't be served from cache); 3 when the run
    recorded no prewarm data (a prewarm gate with nothing to read must
    not pass silently); 2 when ``run_dir`` is not a directory."""
    run_dir = Path(run_dir)
    if not run_dir.is_dir():
        return {"dir": str(run_dir), "error": "not a directory", "exit": 2}, 2
    try:
        run = load_run(run_dir)
    except (OSError, ValueError):
        run = {"manifest": {}, "events": [], "bad_event_lines": 0}
    events = [e for e in run["events"] if e.get("kind") == "prewarm"]
    manifest_block = (run["manifest"] or {}).get("prewarm") or {}
    if not events and not manifest_block:
        return {
            "dir": str(run_dir),
            "error": "no prewarm data (no prewarm events or manifest "
            "roll-up — was the run served with SBR_PREWARM=1?)",
            "exit": 3,
        }, 3

    actions: dict = {}
    abandoned = {"stale": 0, "budget": 0}
    sources: dict = {}
    plans: dict = {}
    for e in events:
        a = str(e.get("action") or "?")
        actions[a] = actions.get(a, 0) + 1
        fp = e.get("fingerprint")
        if fp:
            p = plans.setdefault(str(fp), {
                "tiles": None, "tiles_done": 0, "failed_tiles": 0,
                "adopted": 0, "warm": None, "done": False,
                "rejected": False,
            })
        if a == "plan" and fp:
            p["tiles"] = e.get("tiles")
        elif a == "tile" and fp:
            p["tiles_done"] += 1
            src = str(e.get("source") or "?")
            sources[src] = sources.get(src, 0) + 1
        elif a == "tile_failed" and fp:
            p["failed_tiles"] += 1
        elif a == "adopt" and fp:
            p["adopted"] += 1
        elif a == "abandon":
            reason = str(e.get("reason") or "unknown")
            abandoned[reason] = abandoned.get(reason, 0) + int(e.get("count") or 1)
        elif a == "plan_done" and fp:
            p["done"] = True
            p["warm"] = e.get("warm")
            if e.get("tiles") is not None:
                p["tiles"] = e.get("tiles")
        elif a == "plan_reject" and fp:
            p["rejected"] = True
    if not events and manifest_block:
        # Torn/absent event log: the manifest roll-up still gates.
        actions = {k: v for k, v in manifest_block.items()
                   if isinstance(v, int) and not k.startswith("abandoned_")
                   and not k.startswith("last_")}
        for reason in ("stale", "budget"):
            abandoned[reason] = int(manifest_block.get(f"abandoned_{reason}") or 0)
        fp = manifest_block.get("last_plan")
        if fp:
            plans[str(fp)] = {
                "tiles": manifest_block.get("last_tiles"),
                "tiles_done": int(manifest_block.get("tile") or 0),
                "failed_tiles": int(manifest_block.get("tile_failed") or 0),
                "adopted": int(manifest_block.get("adopt") or 0),
                "warm": manifest_block.get("last_warm"),
                "done": bool(manifest_block.get("plan_done")),
                "rejected": bool(manifest_block.get("plan_reject")),
            }

    breaches = []
    if abandoned.get("budget"):
        breaches.append(
            f"{abandoned['budget']} tile(s) abandoned over the work budget "
            "(raise SBR_PREWARM_BUDGET_TILES/_SECONDS or shrink the plan)"
        )
    for fp, p in sorted(plans.items()):
        if p["done"] and p["warm"] is not None and p["tiles"] is not None \
                and int(p["warm"]) < int(p["tiles"]):
            breaches.append(
                f"plan {fp} completed cold: warm {p['warm']}/{p['tiles']} "
                "tile(s) in the cache"
            )
    code = 1 if breaches else 0
    doc = {
        "dir": str(run_dir),
        "actions": {k: actions[k] for k in sorted(actions)},
        "plans": {k: plans[k] for k in sorted(plans)},
        "sources": {k: sources[k] for k in sorted(sources)},
        "abandoned": abandoned,
        "bad_event_lines": run["bad_event_lines"],
        "breaches": breaches,
        "exit": code,
    }
    return doc, code


def render_prewarm(doc: dict) -> str:
    """Human-readable prewarm report; same exit contract as `prewarm_doc`."""
    out = [f"run      {doc['dir']}"]
    if doc["exit"] in (2, 3):
        out.append(doc.get("error", "no prewarm data"))
        return "\n".join(out)
    plans = doc["plans"]
    done = sum(1 for p in plans.values() if p["done"])
    out.append(
        f"prewarm  {len(plans)} plan(s) seen, {done} completed; "
        f"{sum(p['tiles_done'] for p in plans.values())} tile(s) swept"
    )
    if doc["sources"]:
        out.append("sources  " + ", ".join(
            f"{k}={v}" for k, v in doc["sources"].items()
        ))
    if any(doc["abandoned"].values()):
        out.append("abandoned " + ", ".join(
            f"{k}={v}" for k, v in sorted(doc["abandoned"].items()) if v
        ))
    if doc.get("bad_event_lines"):
        out.append(f"warning  {doc['bad_event_lines']} torn event line(s) skipped")
    if plans:
        out += ["", "PLANS"]
        out.append(_table(
            ["plan", "tiles", "done", "failed", "adopted", "warm", "status"],
            [
                [
                    fp,
                    "-" if p["tiles"] is None else p["tiles"],
                    p["tiles_done"],
                    p["failed_tiles"],
                    p["adopted"],
                    "-" if p["warm"] is None else p["warm"],
                    "rejected" if p["rejected"]
                    else ("done" if p["done"] else "in-flight"),
                ]
                for fp, p in sorted(plans.items())
            ],
        ))
    out.append("")
    if doc["breaches"]:
        out.append("GATE: PREWARM DEGRADED")
        for b in doc["breaches"]:
            out.append(f"  {b}")
    else:
        out.append("GATE: ok (no budget abandonment, completed plans warm)")
    return "\n".join(out)


def _main_prewarm(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report prewarm",
        description="Prefetch-controller report for one run dir "
        "(prewarm events from sbr_tpu.serve.prewarm): per-plan sweep "
        "progress, tile sources, adoption and abandonment; exit 1 when "
        "tiles were abandoned over budget or a completed plan left the "
        "hot region cold, 3 when the run recorded no prewarm data",
    )
    parser.add_argument("run_dir", help="obs run directory of a prewarm-enabled "
                        "engine or standalone sweeper")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = prewarm_doc(args.run_dir)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_prewarm(doc))
    return code


# ---------------------------------------------------------------------------
# Pipeline-utilization report (`util` subcommand — ISSUE 20 flight gate)
# ---------------------------------------------------------------------------


def util_doc(run_dir, floor=None, min_disp=None) -> tuple:
    """Machine-readable pipeline-utilization report (`sbr_tpu.obs.flight`):
    reads the run's rolling ``flight.json`` and derives (or re-derives,
    when only raw records landed) the device-busy / host-gap surface with
    per-cause bubble attribution. Returns (doc, exit_code).

    Exit codes: 0 healthy; 1 when the device-busy fraction is under the
    floor (``--floor`` or ``SBR_FLIGHT_UTIL_FLOOR``) over a measured
    window with at least ``--min-dispatches`` dispatches (fewer disarms
    the gate — a one-dispatch window is compile shadow, not utilization);
    3 when the run recorded no flight data (a gate with nothing to read
    must not pass silently); 2 when ``run_dir`` is not a directory."""
    from sbr_tpu.obs import flight as _flight

    run_dir = Path(run_dir)
    if not run_dir.is_dir():
        return {"dir": str(run_dir), "error": "not a directory", "exit": 2}, 2
    if floor is None:
        floor = _flight.util_floor()
    if min_disp is None:
        min_disp = _flight.min_dispatches()
    try:
        snap = json.loads((run_dir / "flight.json").read_text())
    except (OSError, json.JSONDecodeError, ValueError):
        snap = None
    if not isinstance(snap, dict) or not snap.get("records"):
        return {
            "dir": str(run_dir),
            "error": "no flight data (no flight.json with records — was "
            "the run served with SBR_FLIGHT=1?)",
            "exit": 3,
        }, 3
    # Re-derive from the raw ring rather than trusting the embedded util
    # block: the gate must judge with ITS deriver, and a snapshot written
    # by an older process stays readable.
    util = _flight.derive_utilization(snap)

    breaches = []
    notes = []
    busy = util.get("device_busy_frac")
    dispatches = int(util.get("dispatches") or 0)
    if util.get("dropped_records"):
        notes.append(
            f"{util['dropped_records']} record(s) overwritten in the ring "
            "(raise SBR_FLIGHT_CAP for a wider window)"
        )
    if floor is not None:
        if dispatches < int(min_disp):
            notes.append(
                f"floor gate disarmed: {dispatches} dispatch(es) in the "
                f"window (< {int(min_disp)})"
            )
        elif busy is not None and busy < float(floor):
            causes = util.get("gap_causes") or {}
            top = max(causes.items(), key=lambda kv: kv[1]["s"])[0] \
                if causes else "?"
            breaches.append(
                f"device-busy fraction {busy:.4f} under floor "
                f"{float(floor):g} over {dispatches} dispatch(es) "
                f"(dominant gap cause: {top})"
            )
    code = 1 if breaches else 0
    doc = {
        "dir": str(run_dir),
        "floor": float(floor) if floor is not None else None,
        "min_dispatches": int(min_disp),
        "ts": snap.get("ts"),
        "util": util,
        "notes": notes,
        "breaches": breaches,
        "exit": code,
    }
    return doc, code


def render_util(doc: dict) -> str:
    """Human-readable utilization report; same exit contract as `util_doc`."""
    out = [f"run      {doc['dir']}"]
    if doc["exit"] in (2, 3):
        out.append(doc.get("error", "no flight data"))
        return "\n".join(out)
    u = doc["util"]
    busy = u.get("device_busy_frac")
    gap = u.get("host_gap_frac")
    out.append(
        f"flight   {u.get('records', 0)} record(s), "
        f"{u.get('dispatches', 0)} dispatch(es), "
        f"window {u.get('window_s') or 0:g} s"
    )
    out.append(
        "util     device-busy "
        + ("-" if busy is None else f"{busy:.4f}")
        + "  host-gap "
        + ("-" if gap is None else f"{gap:.4f}")
        + (f"  (floor {doc['floor']:g})" if doc.get("floor") is not None
           else "  (no floor set)")
    )
    causes = u.get("gap_causes") or {}
    if causes:
        out += ["", "GAP ATTRIBUTION"]
        out.append(_table(
            ["cause", "seconds", "share"],
            [
                [c, f"{v['s']:.6f}", f"{v['frac']:.4f}"]
                for c, v in sorted(causes.items(),
                                   key=lambda kv: -kv[1]["s"])
            ],
        ))
    qd = u.get("queue_depth")
    if qd:
        out.append(
            f"queue    p50={qd['p50']:g} p95={qd['p95']:g} "
            f"p99={qd['p99']:g} max={qd['max']:g} "
            f"({qd['samples']} sample(s))"
        )
    occ = u.get("occupancy")
    if occ:
        out.append(
            f"occupancy mean={occ['mean']:g} " + " ".join(
                f"{b}={v:g}" for b, v in occ["by_bucket"].items()
            )
        )
    sw = u.get("sweeps")
    if sw:
        out.append(
            f"sweeps   {sw['tiles']} tile(s), "
            + ", ".join(f"{k}={v:g}ms" for k, v in sw["by_kind_ms"].items())
            + f"; bubbles {sw['bubble_total_ms']:g} ms total"
        )
    col = u.get("collectives")
    if col:
        out.append("collectives " + ", ".join(
            f"{k}: {v['count']}x/{v['total_ms']:g}ms" for k, v in col.items()
        ))
    for n in doc.get("notes") or []:
        out.append(f"note     {n}")
    out.append("")
    if doc["breaches"]:
        out.append("GATE: UTILIZATION DEGRADED")
        for b in doc["breaches"]:
            out.append(f"  {b}")
    else:
        out.append("GATE: ok (device-busy fraction at or above floor)")
    return "\n".join(out)


def _main_util(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report util",
        description="Pipeline-utilization report for one run dir "
        "(flight.json from sbr_tpu.obs.flight): device-busy fraction, "
        "host-gap attribution, queue depth, batch occupancy; exit 1 when "
        "device-busy is under the floor over a measured window, 3 when "
        "the run recorded no flight data",
    )
    parser.add_argument("run_dir", help="obs run directory of a "
                        "flight-enabled (SBR_FLIGHT=1) engine")
    parser.add_argument(
        "--floor", type=float, default=None,
        help="device-busy floor (default: SBR_FLIGHT_UTIL_FLOOR; "
        "unset = gate disarmed)",
    )
    parser.add_argument(
        "--min-dispatches", type=int, default=None, dest="min_dispatches",
        help="dispatches required before the floor gate arms "
        "(default: SBR_FLIGHT_MIN_DISPATCHES or 3)",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = util_doc(args.run_dir, floor=args.floor,
                         min_disp=args.min_dispatches)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_util(doc))
    return code


# ---------------------------------------------------------------------------
# Infomodel report (`infomodel` subcommand — information-model gate)
# ---------------------------------------------------------------------------


def infomodel_doc(run_dir) -> tuple:
    """Machine-readable information-model report (`sbr_tpu.infomodels`):
    the manifest ``infomodel`` roll-up plus the per-event fold (rewire
    epochs, belief censuses, fixed-point solves, closure comparisons,
    population queries). Returns (doc, exit_code).

    Exit codes: 0 healthy; 1 when a mean-field fixed point failed to
    converge (``nonconverged``) or a closure comparison exceeded its
    RECORDED tolerance (``breaches`` — closure events carry err_aw_sup +
    tolerance when the caller supplied one); 3 when the run recorded no
    infomodel data at all (a gate with nothing to read must not pass
    silently); 2 when ``run_dir`` is not a directory."""
    run_dir = Path(run_dir)
    if not run_dir.is_dir():
        return {"dir": str(run_dir), "error": "not a directory", "exit": 2}, 2
    try:
        run = load_run(run_dir)
    except (FileNotFoundError, json.JSONDecodeError) as err:
        return {"dir": str(run_dir), "error": str(err), "exit": 2}, 2
    manifest_blk = run["manifest"].get("infomodel") or {}
    events = [ev for ev in run["events"] if ev.get("kind") == "infomodel"]
    if not manifest_blk and not events:
        return {
            "dir": str(run_dir),
            "error": "no infomodel data (no manifest roll-up, no infomodel events)",
            "exit": 3,
        }, 3
    # The event fold is the kill -9 fallback (a process that died before
    # finalize wrote no manifest roll-up): take the max of the two views
    # per action, the `report fleet` discipline.
    fold: dict = {}
    fixed_points = []
    closures = []
    populations = []
    epochs_by_channel: dict = {}
    for ev in events:
        action = str(ev.get("action", "?"))
        fold[action] = fold.get(action, 0) + 1
        if action == "fixed_point":
            fixed_points.append(
                {k: ev.get(k) for k in (
                    "channel", "dynamics", "groups", "converged", "aborted",
                    "iterations", "xi", "bankrun",
                )}
            )
            if ev.get("converged") is False:
                fold["nonconverged"] = fold.get("nonconverged", 0) + 1
        elif action == "closure":
            rec = {k: ev.get(k) for k in (
                "channel", "dynamics", "n_agents", "n_reps", "err_aw_sup",
                "err_g_rms", "tolerance",
            )}
            err, tol = rec.get("err_aw_sup"), rec.get("tolerance")
            rec["breach"] = (
                isinstance(err, (int, float))
                and isinstance(tol, (int, float))
                and err > tol
            )
            if rec["breach"]:
                fold["breaches"] = fold.get("breaches", 0) + 1
            closures.append(rec)
        elif action == "population_query":
            populations.append(
                {k: ev.get(k) for k in (
                    "channel", "dynamics", "vary", "seeds", "n_agents",
                    "run_probability",
                )}
            )
        elif action == "rewire_epoch":
            ch = str(ev.get("channel", "?"))
            epochs_by_channel[ch] = epochs_by_channel.get(ch, 0) + 1
    counts = {
        k: max(int(manifest_blk.get(k, 0)), int(fold.get(k, 0)))
        for k in set(manifest_blk) | set(fold)
    }
    nonconverged = counts.get("nonconverged", 0)
    breaches = counts.get("breaches", 0)
    breach_msgs = []
    if nonconverged:
        breach_msgs.append(f"{nonconverged} non-converged fixed point(s)")
    if breaches:
        breach_msgs.append(f"{breaches} closure comparison(s) over tolerance")
    code = 1 if breach_msgs else 0
    doc = {
        "dir": str(run_dir),
        "counts": counts,
        "manifest_infomodel": manifest_blk or None,
        "fixed_points": fixed_points,
        "closures": closures,
        "population_queries": populations,
        "rewire_epochs": epochs_by_channel,
        "nonconverged": nonconverged,
        "breaches_count": breaches,
        "breaches": breach_msgs,
        "bad_event_lines": run.get("bad_event_lines", 0),
        "exit": code,
    }
    return doc, code


def render_infomodel(doc: dict) -> str:
    """Human-readable information-model report; same exit contract as
    `infomodel_doc`."""
    out = [f"run      {doc['dir']}"]
    if doc["exit"] in (2, 3):
        out.append(doc.get("error", "no infomodel data"))
        if doc["exit"] == 3:
            out.append(
                "was the run produced with sbr_tpu.infomodels telemetry on "
                "(fixed points / simulate_info / close_loop emit infomodel "
                "events)?"
            )
        return "\n".join(out)
    c = doc["counts"]
    out.append(
        "infomodel "
        + ", ".join(
            f"{int(c.get(k, 0))} {k}" for k in (
                "fixed_point", "closure", "population_query", "rewire_epoch",
                "belief_census",
            ) if c.get(k)
        )
    )
    if doc["fixed_points"]:
        out += ["", "FIXED POINTS"]
        out.append(
            _table(
                ["channel", "dynamics", "groups", "converged", "iters", "xi", "bankrun"],
                [
                    [
                        fp.get("channel", "?"), fp.get("dynamics", "?"),
                        fp.get("groups", 1),
                        fp.get("converged"), fp.get("iterations"),
                        "-" if fp.get("xi") is None else f"{fp['xi']:.4f}",
                        fp.get("bankrun"),
                    ]
                    for fp in doc["fixed_points"]
                ],
            )
        )
    if doc["closures"]:
        out += ["", "CLOSURES"]
        out.append(
            _table(
                ["channel", "dynamics", "agents", "reps", "err_aw_sup", "tol", "ok"],
                [
                    [
                        cl.get("channel", "?"), cl.get("dynamics", "?"),
                        cl.get("n_agents"), cl.get("n_reps"),
                        "-" if cl.get("err_aw_sup") is None else f"{cl['err_aw_sup']:.4f}",
                        "-" if cl.get("tolerance") is None else f"{cl['tolerance']:g}",
                        "BREACH" if cl.get("breach") else "ok",
                    ]
                    for cl in doc["closures"]
                ],
            )
        )
    if doc["population_queries"]:
        out += ["", "POPULATION QUERIES"]
        out.append(
            _table(
                ["channel", "dynamics", "vary", "seeds", "agents", "run_p"],
                [
                    [
                        p.get("channel", "?"), p.get("dynamics", "?"),
                        p.get("vary", "?"), p.get("seeds"), p.get("n_agents"),
                        "-" if p.get("run_probability") is None
                        else f"{p['run_probability']:.3f}",
                    ]
                    for p in doc["population_queries"]
                ],
            )
        )
    if doc["rewire_epochs"]:
        out.append(
            "epochs   "
            + ", ".join(f"{ch}: {n}" for ch, n in sorted(doc["rewire_epochs"].items()))
        )
    if doc["breaches"]:
        out += [""] + [f"BREACH   {b}" for b in doc["breaches"]]
    if doc.get("bad_event_lines"):
        out.append(f"warning  {doc['bad_event_lines']} unparseable event line(s)")
    return "\n".join(out)


def _main_infomodel(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report infomodel",
        description="Information-model report for one run (fixed points, "
        "closure comparisons, rewire epochs, population queries); exit 1 "
        "on a non-converged fixed point or a closure comparison over its "
        "recorded tolerance, 3 when no infomodel data was recorded",
    )
    parser.add_argument("run_dir", help="run directory (contains events.jsonl)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = infomodel_doc(args.run_dir)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_infomodel(doc))
    return code


# ---------------------------------------------------------------------------
# Memory report (`memory` subcommand — the obs.mem attribution renderer/gate)
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# Grad report (`grad` subcommand — differentiable-equilibria renderer/gate)
# ---------------------------------------------------------------------------


def _grad_fold(events) -> dict:
    """Fold ``grad`` events (the `sbr_tpu.grad` emissions): calibration
    runs (start/step/done series), gradient-flag censuses per stage, and
    stress-search outcomes."""
    calibrations = []
    current = None
    censuses = []
    stress = []
    for ev in events:
        if ev.get("kind") != "grad":
            continue
        action = ev.get("action")
        if action == "calib_start":
            current = {
                "wrt": ev.get("wrt"), "budget": ev.get("steps"),
                "n_obs": ev.get("n_obs"), "with_xi": ev.get("with_xi"),
                "losses": [],
            }
            calibrations.append(current)
        elif action == "calib_step":
            if current is not None:
                current["losses"].append(float(ev.get("loss", float("nan"))))
        elif action == "calib_done":
            rec = current if current is not None else {"losses": []}
            rec["steps"] = ev.get("steps")
            rec["loss"] = ev.get("loss")
            rec["converged"] = bool(ev.get("converged"))
            rec["fit"] = {
                k[len("fit_"):]: v for k, v in ev.items() if k.startswith("fit_")
            }
            if current is None:
                calibrations.append(rec)
            current = None
        elif action == "flags":
            censuses.append({
                k: ev.get(k)
                for k in ("stage", "cells", "run_cells", "at_nonequilibrium",
                          "ill_conditioned", "nonfinite", "nonfinite_run",
                          "untrusted")
            })
        elif action == "stress_done":
            stress.append({
                k: v for k, v in ev.items()
                if k not in ("kind", "ts", "mono", "action")
            })
    return {"calibrations": calibrations, "censuses": censuses, "stress": stress}


def grad_doc(run: dict) -> tuple:
    """Machine-readable differentiable-equilibria report; (doc, exit_code).

    Exit contract (matching the other subcommands): 0 healthy, 1 when a
    calibration finished unconverged or any flag census recorded NONFINITE
    gradients AT RUN CELLS (``nonfinite_run`` — NaN sensitivities on
    no-run lanes are the expected face of degenerate brackets, and
    at_nonequilibrium / ill_conditioned are informational: a sensitivity
    surface legitimately spans no-run cells), 3 when the run carries no
    grad data at all (a gate with nothing to read must not pass
    silently); the CLI returns 2 on an unreadable run dir.
    """
    folded = _grad_fold(run["events"])
    has_data = any(folded.values())
    if not has_data:
        code = 3
    else:
        # `converged is False` only: a record without the key is a
        # calibration still RUNNING (calib_start seen, calib_done not yet)
        # — reading a live run dir must not produce a false-red gate.
        bad_calib = any(
            c.get("converged") is False for c in folded["calibrations"]
        )
        bad_grads = any(
            int(c.get("nonfinite_run") or 0) > 0 for c in folded["censuses"]
        )
        code = 1 if (bad_calib or bad_grads) else 0
    doc = {
        "dir": run["dir"],
        **folded,
        "bad_event_lines": run.get("bad_event_lines", 0),
        "exit": code,
    }
    return doc, code


def render_grad(run: dict) -> tuple:
    """Human-readable grad report; same exit contract as `grad_doc`."""
    from sbr_tpu.obs.history import sparkline

    doc, code = grad_doc(run)
    out = [f"run      {run['dir']}{_bad_lines_note(run)}"]
    if code == 3:
        out.append(
            "no grad events recorded — did the run use sbr_tpu.grad "
            "(xi_and_grad / sensitivity_surface / fit_withdrawals)?"
        )
        return "\n".join(out), code
    if doc["calibrations"]:
        out += ["", "CALIBRATIONS"]
        rows = []
        for c in doc["calibrations"]:
            fit = c.get("fit") or {}
            rows.append([
                ",".join(c.get("wrt") or fit.keys()),
                c.get("steps", "-"),
                f"{c['loss']:.3e}" if isinstance(c.get("loss"), float) else "-",
                "yes" if c.get("converged") else "NO",
                sparkline(c.get("losses") or []) or "-",
                " ".join(f"{k}={v:.4g}" for k, v in fit.items()) or "-",
            ])
        out.append(_table(["wrt", "steps", "loss", "converged", "trend", "fitted"], rows))
    if doc["censuses"]:
        out += ["", "GRADIENT FLAG CENSUS"]
        out.append(
            _table(
                ["stage", "cells", "run", "non-eq", "ill-cond", "nonfinite", "nonfin@run"],
                [
                    [
                        c.get("stage", "?"), c.get("cells", "-"),
                        c.get("run_cells", "-"), c.get("at_nonequilibrium", 0),
                        c.get("ill_conditioned", 0), c.get("nonfinite", 0),
                        c.get("nonfinite_run", 0),
                    ]
                    for c in doc["censuses"]
                ],
            )
        )
    if doc["stress"]:
        out += ["", "STRESS SEARCHES"]
        out.append(
            _table(
                ["flipped", "validated", "steps", "shock", "margin0", "margin*"],
                [
                    [
                        "yes" if s.get("flipped") else "no",
                        "yes" if s.get("validated") else "-",
                        s.get("steps", "-"),
                        f"{s['shock_norm']:.4g}" if isinstance(s.get("shock_norm"), float) else "-",
                        f"{s['margin0']:.3g}" if isinstance(s.get("margin0"), float) else "-",
                        f"{s['margin_final']:.3g}" if isinstance(s.get("margin_final"), float) else "-",
                    ]
                    for s in doc["stress"]
                ],
            )
        )
    if code == 1:
        out += ["", "GATE: unconverged calibration or non-finite gradients (exit 1)"]
    return "\n".join(out), code


def _main_grad(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report grad",
        description="Differentiable-equilibria report for one run "
        "(calibration convergence, gradient-flag census, stress searches); "
        "exit 1 on unconverged calibration / non-finite gradients, 3 when "
        "no grad data was recorded",
    )
    parser.add_argument("run_dir", help="run directory (contains manifest.json)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    try:
        run = load_run(args.run_dir)
    except (FileNotFoundError, json.JSONDecodeError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    if args.json:
        doc, code = grad_doc(run)
        print(json.dumps(doc, default=str))
        return code
    text, code = render_grad(run)
    print(text)
    return code


def _mem_fold(events) -> dict:
    """Fold ``mem`` events: per-where maxima for span attribution, per-tile
    peaks, and the last observed device capacity. The event log is the
    source of truth even when a kill -9 meant the manifest roll-up was
    never finalized (same contract as the resilience report)."""
    spans: dict = {}
    tiles: dict = {}
    capacity = None
    for ev in events:
        if ev.get("kind") != "mem":
            continue
        if isinstance(ev.get("bytes_limit"), (int, float)) and ev["bytes_limit"] > 0:
            capacity = int(ev["bytes_limit"])
        tile = ev.get("tile")
        if tile:
            tiles[tile] = max(tiles.get(tile, 0), _tile_peak(ev))
            continue
        agg = spans.setdefault(
            ev.get("where", "?"),
            {"events": 0, "live_buffer_bytes": 0, "bytes_in_use": 0, "peak_bytes_in_use": 0},
        )
        agg["events"] += 1
        for k in ("live_buffer_bytes", "bytes_in_use", "peak_bytes_in_use"):
            if isinstance(ev.get(k), (int, float)):
                agg[k] = max(agg[k], int(ev[k]))
    return {"spans": spans, "tiles": tiles, "capacity_bytes": capacity}


def memory_doc(run: dict, headroom_override=None) -> tuple:
    """Machine-readable memory report; returns (doc, exit_code). Exit
    codes: 0 within budget, 1 when any tile's peak exceeds the headroom
    threshold (or a preflight verdict was "exceeds"), 3 when the run
    carries no memory data at all (an instrumented run that was supposed
    to attribute memory but emitted nothing must not pass a gate
    silently)."""
    m = run["manifest"].get("memory") or {}
    folded = _mem_fold(run["events"])
    tiles = {k: int(v) for k, v in (m.get("tiles") or {}).items()}
    for t, p in folded["tiles"].items():
        tiles[t] = max(tiles.get(t, 0), p)
    capacity = m.get("capacity_bytes") or folded["capacity_bytes"]
    headroom = (
        float(headroom_override)
        if headroom_override is not None
        else float(m.get("headroom") or 0.8)
    )
    preflight = m.get("preflight") or [
        {k: v for k, v in ev.items() if k not in ("mono", "ts", "kind")}
        for ev in run["events"]
        if ev.get("kind") == "preflight"
    ]
    plan = m.get("plan")
    has_data = bool(
        folded["spans"]
        or tiles
        or m.get("peak_live_buffer_bytes")
        or m.get("peak_device_bytes")
        or plan
        or preflight
    )
    threshold = int(capacity * headroom) if capacity else None
    over = sorted(t for t, p in tiles.items() if threshold is not None and p > threshold)
    preflight_exceeded = any(p.get("verdict") == "exceeds" for p in preflight)
    code = 3 if not has_data else (1 if (over or preflight_exceeded) else 0)
    doc = {
        "dir": run["dir"],
        "memory": m,
        "spans": folded["spans"],
        "tiles": tiles,
        "capacity_bytes": capacity,
        "headroom": headroom,
        "threshold_bytes": threshold,
        "over_tiles": over,
        "preflight": preflight,
        "plan": plan,
        "bad_event_lines": run.get("bad_event_lines", 0),
        "exit": code,
    }
    return doc, code


def render_memory(run: dict, headroom_override=None) -> tuple:
    """Human-readable memory report; same exit-code contract as
    `memory_doc`."""
    doc, code = memory_doc(run, headroom_override)
    m = doc["memory"]
    out = [f"run      {run['dir']}{_bad_lines_note(run)}"]
    if code == 3:
        out.append(
            "no memory data recorded — was the run produced by an "
            "instrumented sweep/solve with telemetry on?"
        )
        return "\n".join(out), code
    peak = m.get("peak_device_bytes") or m.get("peak_live_buffer_bytes") or 0
    out.append(
        f"memory   peak {_fmt_bytes(peak)}"
        + (f"   in span {m['peak_span']}" if m.get("peak_span") else "")
    )
    if doc["capacity_bytes"]:
        out.append(
            f"capacity {_fmt_bytes(doc['capacity_bytes'])}   headroom "
            f"{doc['headroom']:.0%} → threshold {_fmt_bytes(doc['threshold_bytes'])}"
        )
    else:
        out.append("capacity unknown (no allocator stats — CPU backend?)")

    if doc["plan"]:
        p = doc["plan"]
        out += ["", "CAPACITY PLAN"]
        out.append(
            f"tile_shape auto → {tuple(p.get('tile_shape', []))} "
            f"(verdict {p.get('verdict')}"
            + (
                f", modeled {_fmt_bytes(p['modeled_bytes'])} of budget "
                f"{_fmt_bytes(p['budget_bytes'])}"
                if p.get("modeled_bytes") is not None
                else ""
            )
            + ")"
        )
    if doc["preflight"]:
        out += ["", "PREFLIGHT"]
        out.append(
            _table(
                ["label", "verdict", "footprint", "budget"],
                [
                    [
                        p.get("label", "-"),
                        p.get("verdict", "-").upper()
                        if p.get("verdict") == "exceeds"
                        else p.get("verdict", "-"),
                        _fmt_bytes(p.get("footprint_bytes")),
                        _fmt_bytes(p.get("budget_bytes")),
                    ]
                    for p in doc["preflight"]
                ],
            )
        )
    if doc["spans"]:
        out += ["", "SPANS (peak bytes observed at span/jit boundaries)"]
        out.append(
            _table(
                ["where", "events", "live buffers", "in use", "device peak"],
                [
                    [
                        k,
                        v["events"],
                        _fmt_bytes(v["live_buffer_bytes"]),
                        _fmt_bytes(v["bytes_in_use"]),
                        _fmt_bytes(v["peak_bytes_in_use"]),
                    ]
                    for k, v in sorted(doc["spans"].items())
                ],
            )
        )
    if doc["tiles"]:
        out += ["", f"TILES{' (OVER THRESHOLD: ' + ', '.join(doc['over_tiles']) + ')' if doc['over_tiles'] else ''}"]
        rows = []
        for tile, peak_b in sorted(doc["tiles"].items(), key=lambda kv: -kv[1]):
            share = (
                f"{100 * peak_b / doc['capacity_bytes']:.1f}%"
                if doc["capacity_bytes"]
                else "-"
            )
            rows.append(
                [tile, _fmt_bytes(peak_b), share, "OVER" if tile in doc["over_tiles"] else "-"]
            )
        out.append(_table(["tile", "peak", "of capacity", "flag"], rows))
    top = m.get("top_programs") or []
    if top:
        out += ["", "TOP PROGRAMS (by XLA temp size)"]
        out.append(
            _table(
                ["program", "temp", "output", "arguments"],
                [
                    [
                        p.get("name", "-"),
                        _fmt_bytes(p.get("temp_bytes")),
                        _fmt_bytes(p.get("out_bytes")),
                        _fmt_bytes(p.get("arg_bytes")),
                    ]
                    for p in top
                ],
            )
        )
    return "\n".join(out), code


# ---------------------------------------------------------------------------
# Serve report (`serve` subcommand — the live serving-telemetry renderer/gate)
# ---------------------------------------------------------------------------


def _env_float(name: str, default) -> float:
    import os

    raw = os.environ.get(name, "").strip()
    return float(raw) if raw else default


def serve_doc(run_dir, slo_ms=None, cache_floor=None, warmup=None) -> tuple:
    """Machine-readable serve report from a run dir's rolling ``live.json``
    (written by `sbr_tpu.serve.engine` — atomic rename, so a RUNNING server
    can be read mid-flight); returns (doc, exit_code).

    Exit codes: 0 within SLO, 1 on a breach — window p99 over
    ``SBR_SERVE_SLO_MS`` (when set), or cache hit rate under the floor
    (``SBR_SERVE_CACHE_FLOOR``, default 0 = disabled) after warmup
    (``SBR_SERVE_WARMUP`` lifetime queries, default 50) — 2 when
    ``run_dir`` does not exist, 3 when no live serving data was recorded
    (a serve gate with nothing to read must not pass silently).
    """
    run_dir = Path(run_dir)
    if not run_dir.is_dir():
        return {"dir": str(run_dir), "error": "not a directory", "exit": 2}, 2
    live_path = run_dir / "live.json"
    try:
        live = json.loads(live_path.read_text())
    except (OSError, json.JSONDecodeError) as err:
        return {
            "dir": str(run_dir),
            "error": f"no readable live.json ({err})",
            "exit": 3,
        }, 3

    slo_ms = _env_float("SBR_SERVE_SLO_MS", None) if slo_ms is None else slo_ms
    cache_floor = (
        _env_float("SBR_SERVE_CACHE_FLOOR", 0.0) if cache_floor is None else cache_floor
    )
    warmup = int(_env_float("SBR_SERVE_WARMUP", 50)) if warmup is None else int(warmup)

    totals = live.get("totals") or {}
    window = live.get("window") or {}
    # The rolling window is the live view; when it has drained (a finished
    # server read post-hoc after >window_s), fall back to lifetime numbers.
    in_window = bool(window.get("queries"))
    scope = window if in_window else totals
    scope_name = "window" if in_window else "lifetime"
    p99 = (scope.get("latency_ms") or {}).get("p99")
    hit_rate = scope.get("hit_rate")
    scope_queries = scope.get("queries", 0)

    breaches = []
    if slo_ms is not None and p99 is not None and p99 > slo_ms:
        breaches.append(f"p99 {p99:.3f} ms over SLO {slo_ms:g} ms ({scope_name})")
    # The rate and the arming count come from the SAME scope: a quiet
    # window holding two fresh queries on a long-warm server must not read
    # as a cold cache (the lifetime count would arm the gate while the
    # window rate tanks on two samples).
    if cache_floor > 0 and scope_queries >= warmup and (hit_rate or 0.0) < cache_floor:
        breaches.append(
            f"cache hit rate {0.0 if hit_rate is None else hit_rate:.3f} "
            f"under floor {cache_floor:g} after warmup "
            f"({int(scope_queries)} {scope_name} queries)"
        )
    code = 1 if breaches else 0
    doc = {
        "dir": str(run_dir),
        "live": live,
        "scope": "window" if in_window else "lifetime",
        "slo_ms": slo_ms,
        "cache_floor": cache_floor,
        "warmup": warmup,
        "p99_ms": p99,
        "hit_rate": hit_rate,
        "breaches": breaches,
        "exit": code,
    }
    return doc, code


def render_serve(doc: dict) -> str:
    """Human-readable serve report; same exit contract as `serve_doc`."""
    live = doc.get("live") or {}
    out = [f"run      {doc['dir']}"]
    if doc["exit"] in (2, 3):
        out.append(doc.get("error", "no serving data"))
        if doc["exit"] == 3:
            out.append(
                "was the run produced by sbr_tpu.serve (the engine writes a "
                "rolling live.json)?"
            )
        return "\n".join(out)
    out.append(
        f"serving  started {live.get('started_at')}   uptime "
        f"{_fmt_s(live.get('uptime_s'))}   snapshot age "
        f"{_fmt_s(max(0.0, time.time() - live.get('ts', 0)))}"
    )
    healthz = live.get("healthz") or {}
    out.append(
        f"health   {healthz.get('status', '?')}"
        + (f"   ({'; '.join(healthz.get('reasons', []))})" if healthz.get("reasons") else "")
    )
    engine = live.get("engine") or {}
    if engine:
        out.append(
            f"engine   buckets {engine.get('buckets')}   dtype {engine.get('dtype')}   "
            f"execs {engine.get('compiled', 0)} compiled / {engine.get('loaded', 0)} reloaded   "
            f"lru {engine.get('lru_entries', 0)}/{engine.get('lru_max', '?')}"
        )
    rows = []
    for label, scope in (("window", live.get("window") or {}), ("lifetime", live.get("totals") or {})):
        lat = scope.get("latency_ms") or {}
        rows.append(
            [
                label,
                int(scope.get("queries", 0)),
                "-" if scope.get("hit_rate") is None else f"{scope['hit_rate']:.1%}",
                "-" if scope.get("occupancy") is None else f"{scope['occupancy']:.1%}",
                int(scope.get("divergent_cells", 0)),
                *(
                    "-" if lat.get(q) is None else f"{lat[q]:.2f}"
                    for q in ("p50", "p95", "p99")
                ),
            ]
        )
    out += ["", "TRAFFIC"]
    out.append(
        _table(
            ["scope", "queries", "hit rate", "occupancy", "divergent",
             "p50 ms", "p95 ms", "p99 ms"],
            rows,
        )
    )
    compile_blk = live.get("compile") or {}
    out.append(
        f"\ncompiles {int(compile_blk.get('compiles', 0))} XLA backend compile(s), "
        f"traces " + (
            ", ".join(f"{k}={v}" for k, v in (compile_blk.get("traces") or {}).items())
            or "-"
        )
    )
    hist = ((live.get("window") or {}).get("latency_hist_ms")) or {}
    bounds, counts = hist.get("bounds") or [], hist.get("counts") or []
    if bounds and counts and sum(counts):
        buckets = {}
        for i, c in enumerate(counts):
            if not c:
                continue
            label = f"<={bounds[i]:g}ms" if i < len(bounds) else f">{bounds[-1]:g}ms"
            buckets[label] = c
        out += ["", "WINDOW LATENCY HISTOGRAM"]
        out += _ascii_hist(buckets)
    gate_bits = []
    if doc.get("slo_ms") is not None:
        gate_bits.append(f"SLO p99 <= {doc['slo_ms']:g} ms")
    if doc.get("cache_floor"):
        gate_bits.append(f"hit rate >= {doc['cache_floor']:g} after {doc['warmup']} queries")
    out.append("")
    if doc["breaches"]:
        out.append("GATE: SLO BREACH")
        for b in doc["breaches"]:
            out.append(f"  {b}")
    else:
        out.append(
            "GATE: ok" + (f" ({'; '.join(gate_bits)})" if gate_bits else " (no SLO configured)")
        )
    return "\n".join(out)


def _main_serve(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report serve",
        description="Render a serving run's rolling live telemetry "
        "(live.json); exit 1 on SLO breach (p99 over SBR_SERVE_SLO_MS or "
        "cache hit rate under the floor after warmup), 3 when no live "
        "serving data was recorded",
    )
    parser.add_argument("run_dir", help="run directory (contains live.json)")
    parser.add_argument("--slo-ms", type=float, default=None, dest="slo_ms",
                        help="p99 latency SLO in ms (default: $SBR_SERVE_SLO_MS)")
    parser.add_argument("--cache-floor", type=float, default=None, dest="cache_floor",
                        help="minimum cache hit rate after warmup "
                        "(default: $SBR_SERVE_CACHE_FLOOR, else 0 = disabled)")
    parser.add_argument("--warmup", type=int, default=None,
                        help="lifetime queries before the cache floor applies "
                        "(default: $SBR_SERVE_WARMUP, else 50)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = serve_doc(args.run_dir, args.slo_ms, args.cache_floor, args.warmup)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_serve(doc))
    return code


def _main_memory(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report memory",
        description="Per-span/per-tile peak-memory attribution for one run; "
        "exit 1 when any tile exceeds the headroom threshold (or a preflight "
        "failed), 3 when no memory data was recorded",
    )
    parser.add_argument("run_dir", help="run directory (contains manifest.json)")
    parser.add_argument(
        "--headroom", type=float, default=None, metavar="FRAC",
        help="override the flagging threshold as a fraction of device "
        "capacity (default: the run's recorded SBR_MEM_HEADROOM, else 0.8)",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    try:
        run = load_run(args.run_dir)
    except (FileNotFoundError, json.JSONDecodeError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    if args.json:
        doc, code = memory_doc(run, args.headroom)
        print(json.dumps(doc, default=str))
        return code
    text, code = render_memory(run, args.headroom)
    print(text)
    return code


def _main_health(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report health",
        description="Numerical-health report for one run; nonzero exit on divergence",
    )
    parser.add_argument("run_dir", help="run directory (contains manifest.json)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    try:
        run = load_run(args.run_dir)
    except (FileNotFoundError, json.JSONDecodeError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    if args.json:
        doc, code = health_json(run)
        print(json.dumps(doc, default=str))
        return code
    text, code = render_health(run)
    print(text)
    return code


def _main_resilience(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report resilience",
        description="Fault/retry/repair report for one run; nonzero exit on "
        "unrecovered failures (a retry scope that gave up, a repair that failed)",
    )
    parser.add_argument("run_dir", help="run directory (contains manifest.json)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    try:
        run = load_run(args.run_dir)
    except (FileNotFoundError, json.JSONDecodeError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    if args.json:
        doc, code = resilience_json(run)
        print(json.dumps(doc, default=str))
        return code
    text, code = render_resilience(run)
    print(text)
    return code


def _main_gc(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report gc",
        description="Prune old obs run directories (keeping the N most "
        "recent) plus checkpoint debris left by aborted multihost runs: "
        "quarantine/ directories and stale tile_*.lease files",
    )
    parser.add_argument(
        "root", nargs="?", default=None,
        help="run root to prune (default: $SBR_OBS_DIR or obs_runs/)",
    )
    parser.add_argument("--keep", type=int, required=True, metavar="N",
                        help="number of most-recent run directories to keep")
    parser.add_argument(
        "--checkpoints", action="append", default=[], metavar="DIR",
        help="additional checkpoint root(s) to sweep for quarantine/ dirs "
        "and stale tile_*.lease files (the run root is always swept)",
    )
    parser.add_argument(
        "--lease-ttl", type=float, default=900.0, metavar="S",
        help="age (s) past which a lease with no recorded TTL counts as "
        "stale (default 900, matching SBR_STEAL_LEASE_TTL_S)",
    )
    parser.add_argument(
        "--tile-cache", action="append", default=[], metavar="DIR",
        help="cross-run global tile cache root(s) (SBR_TILE_CACHE_DIR) to "
        "prune of COLD entries — not read/written for --keep-days (cache "
        "hits refresh an entry's mtime, so warm regions are never evicted)",
    )
    parser.add_argument(
        "--keep-days", type=float, default=30.0, metavar="N",
        help="age (days) past which an unused tile-cache entry is pruned "
        "(default 30; only with --tile-cache)",
    )
    parser.add_argument(
        "--trace-keep", type=int, default=None, metavar="N", dest="trace_keep",
        help="also prune rotated trace span files (trace.NNN.jsonl) inside "
        "kept run dirs down to the N most recent per dir; live runs and "
        "the active trace.jsonl are never touched",
    )
    parser.add_argument(
        "--audit-keep", type=int, default=None, metavar="N", dest="audit_keep",
        help="also prune audit battery artifacts (audit/battery_NNNN.json) "
        "inside kept run dirs down to the N most recent per dir, plus "
        "archived golden snapshots (goldens_*.NNN.json) in the audit "
        "registry down to N per key; live runs and the active goldens "
        "are never touched",
    )
    parser.add_argument(
        "--demand-keep", type=int, default=None, metavar="N", dest="demand_keep",
        help="also prune rotated demand snapshots (demand.NNN.json) and "
        "aged advisor plans (advisor_plan.NNN.json) inside kept run dirs "
        "down to the N most recent per dir; live runs and the active "
        "demand.json / advisor_plan.json are never touched",
    )
    parser.add_argument(
        "--prewarm-keep", type=int, default=None, metavar="N", dest="prewarm_keep",
        help="also prune completed prewarm plan-state dirs "
        "(plan_<fingerprint>/ under SBR_PREWARM_STATE_DIR or the tile "
        "cache's _prewarm/) down to the N most recent, plus leases whose "
        "tile already carries a done marker; epochs with live leases or "
        "sweeper heartbeats and the newest (active) plan are never touched",
    )
    parser.add_argument(
        "--flight-keep", type=int, default=None, metavar="N", dest="flight_keep",
        help="also prune rotated flight-recorder snapshots "
        "(flight.NNN.json) inside kept run dirs down to the N most recent "
        "per dir; live runs and the active flight.json are never touched",
    )
    args = parser.parse_args(argv)
    import os

    from sbr_tpu.obs import mem
    from sbr_tpu.obs.runlog import gc_runs

    root = args.root or os.environ.get("SBR_OBS_DIR", "obs_runs")
    removed = gc_runs(root, args.keep)
    print(f"removed {len(removed)} run dir(s) under {root} (keep {args.keep})")
    for d in removed:
        print(f"  {d}")
    debris = []
    for r in [root, *args.checkpoints]:
        debris.extend(mem.gc_debris(r, lease_ttl_s=args.lease_ttl))
    print(f"removed {len(debris)} checkpoint-debris path(s) "
          "(quarantine/, stale tile_*.lease, expired host_*.hb)")
    for p in debris:
        print(f"  {p}")
    if args.tile_cache:
        from sbr_tpu.resilience.elastic import gc_tile_cache

        pruned = []
        for c in args.tile_cache:
            pruned.extend(gc_tile_cache(c, keep_days=args.keep_days))
        print(f"removed {len(pruned)} cold tile-cache entr(ies) "
              f"(unused for {args.keep_days:g} days)")
        for p in pruned:
            print(f"  {p}")
    if args.trace_keep is not None:
        from sbr_tpu.obs.trace import gc_trace_files

        pruned = gc_trace_files(root, keep_rotated=args.trace_keep)
        print(f"removed {len(pruned)} rotated trace span file(s) "
              f"(keep {args.trace_keep} per run dir)")
        for p in pruned:
            print(f"  {p}")
    if args.audit_keep is not None:
        from sbr_tpu.obs.audit import gc_audit_files

        pruned = gc_audit_files(root, keep=args.audit_keep)
        print(f"removed {len(pruned)} audit artifact file(s) "
              f"(keep {args.audit_keep} per run dir / golden key)")
        for p in pruned:
            print(f"  {p}")
    if args.demand_keep is not None:
        from sbr_tpu.obs.demand import gc_demand_files

        pruned = gc_demand_files(root, keep=args.demand_keep)
        print(f"removed {len(pruned)} demand artifact file(s) "
              f"(keep {args.demand_keep} per run dir)")
        for p in pruned:
            print(f"  {p}")
    if args.prewarm_keep is not None:
        from sbr_tpu.serve.prewarm import gc_prewarm_files

        pruned = gc_prewarm_files(keep=args.prewarm_keep)
        print(f"removed {len(pruned)} prewarm state path(s) "
              f"(keep {args.prewarm_keep} plan epoch(s))")
        for p in pruned:
            print(f"  {p}")
    if args.flight_keep is not None:
        from sbr_tpu.obs.flight import gc_flight_files

        pruned = gc_flight_files(root, keep=args.flight_keep)
        print(f"removed {len(pruned)} flight artifact file(s) "
              f"(keep {args.flight_keep} per run dir)")
        for p in pruned:
            print(f"  {p}")
    return 0


# ---------------------------------------------------------------------------
# Distributed-trace reports (`trace` / `slo` subcommands — ISSUE 16)
# ---------------------------------------------------------------------------

#: Span names that root a trace in some process (used by `slo` to pick the
#: end-to-end measurement when the cross-process root is ambiguous).
_TRACE_ROOT_NAMES = ("router.request", "worker.request", "loadgen.query")


def _load_fleet_spans(run_dirs) -> tuple:
    """Spans from N run dirs, each tagged with its source dir.

    Returns ``(spans, bad_lines, per_dir)``; raises ``NotADirectoryError``
    for a missing dir (the exit-2 contract every run-dir subcommand keeps).
    """
    from sbr_tpu.obs import trace as qtrace

    spans, bad, per_dir = [], 0, []
    for d in run_dirs:
        if not Path(d).is_dir():
            raise NotADirectoryError(str(d))
        got, b = qtrace.load_spans(d)
        for s in got:
            s["_dir"] = str(d)
        spans.extend(got)
        bad += b
        per_dir.append({"dir": str(d), "spans": len(got), "bad_span_lines": b})
    return spans, bad, per_dir


def _span_attrs(span: dict) -> dict:
    skip = {"trace", "span", "parent", "name", "svc", "ts", "dur_ms", "_dir"}
    return {k: v for k, v in span.items() if k not in skip}


def _join_trace(spans: list) -> dict:
    """Join one trace's spans into a tree; returns the join verdict.

    - root: the unique parentless span (earliest by ts when several claim
      it — a worker-side exemplar whose router half was head-dropped).
    - orphans: spans whose parent id exists nowhere in the trace AND is not
      the root's own remote parent (which legitimately lives upstream).
    - coverage: union of non-root span intervals clipped to the root's
      interval, over the root's duration — "how much of the end-to-end
      latency the waterfall explains".
    """
    ids = {s["span"] for s in spans}
    roots = [s for s in spans if not s.get("parent")]
    root = min(roots, key=lambda s: s.get("ts", 0.0)) if roots else None
    orphans = [
        s for s in spans
        if s.get("parent") and s["parent"] not in ids and s is not root
    ]
    coverage = None
    if root is not None and root.get("dur_ms"):
        r0 = root.get("ts", 0.0)
        r1 = r0 + root["dur_ms"] / 1e3
        ivals = []
        for s in spans:
            if s is root:
                continue
            a = max(s.get("ts", 0.0), r0)
            b = min(s.get("ts", 0.0) + s.get("dur_ms", 0.0) / 1e3, r1)
            if b > a:
                ivals.append((a, b))
        ivals.sort()
        covered, cur_a, cur_b = 0.0, None, None
        for a, b in ivals:
            if cur_b is None or a > cur_b:
                if cur_b is not None:
                    covered += cur_b - cur_a
                cur_a, cur_b = a, b
            else:
                cur_b = max(cur_b, b)
        if cur_b is not None:
            covered += cur_b - cur_a
        coverage = round(covered / max(r1 - r0, 1e-12), 4)
    return {
        "root": root,
        "orphans": orphans,
        "rootless": root is None,
        "coverage": coverage,
        "exemplar": any(s.get("exemplar") for s in spans),
    }


def _waterfall_rows(spans: list, root: dict) -> list:
    """Depth-first waterfall rows (offset from the root's start)."""
    children: dict = {}
    for s in spans:
        children.setdefault(s.get("parent"), []).append(s)
    for kids in children.values():
        kids.sort(key=lambda s: s.get("ts", 0.0))
    r0 = root.get("ts", 0.0)
    rows, seen = [], set()

    def walk(span, depth):
        if id(span) in seen:  # defensive: a span cycle must not hang report
            return
        seen.add(id(span))
        rows.append({
            "name": span.get("name", "?"),
            "svc": span.get("svc", "?"),
            "offset_ms": round((span.get("ts", 0.0) - r0) * 1e3, 3),
            "dur_ms": span.get("dur_ms"),
            "depth": depth,
            "attrs": _span_attrs(span),
        })
        for kid in children.get(span["span"], []):
            walk(kid, depth + 1)

    walk(root, 0)
    # Joinable-but-detached spans (orphans) still show up, flattened at the
    # end, so the waterfall never silently hides data.
    for s in spans:
        if id(s) not in seen:
            rows.append({
                "name": s.get("name", "?"), "svc": s.get("svc", "?"),
                "offset_ms": round((s.get("ts", 0.0) - r0) * 1e3, 3),
                "dur_ms": s.get("dur_ms"), "depth": 1,
                "attrs": dict(_span_attrs(s), detached=True),
            })
    return rows


def trace_doc(run_dirs, max_waterfalls: int = 5) -> tuple:
    """Fleet-wide trace join: spans from the router's and every worker's run
    dir, joined by trace id into per-query waterfalls.

    Exit codes: 0 ok; 1 when a hash-sampled (non-exemplar) trace has
    orphaned or rootless spans — the join gate; 2 bad dir; 3 no spans.
    Exemplar-only traces may legitimately miss their upstream half (the
    other process head-dropped the trace), so they never trip the gate.
    """
    try:
        spans, bad, per_dir = _load_fleet_spans(run_dirs)
    except NotADirectoryError as err:
        return {"error": f"not a run directory: {err}", "exit": 2}, 2
    if not spans:
        return {
            "error": "no trace spans recorded (is SBR_TRACE_SAMPLE set?)",
            "dirs": per_dir, "bad_span_lines": bad, "exit": 3,
        }, 3

    by_trace: dict = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)

    traces, bad_joins = [], []
    for tid, group in sorted(by_trace.items()):
        verdict = _join_trace(group)
        root = verdict["root"]
        has_failover = any(
            s.get("name") == "router.forward" and s.get("outcome") == "error"
            for s in group
        )
        has_hedge = any(s.get("role") == "hedge" for s in group)
        entry = {
            "trace": tid,
            "spans": len(group),
            "services": sorted({s.get("svc", "?") for s in group}),
            "root": root.get("name") if root else None,
            "dur_ms": root.get("dur_ms") if root else None,
            "coverage": verdict["coverage"],
            "orphans": len(verdict["orphans"]),
            "rootless": verdict["rootless"],
            "exemplar": verdict["exemplar"],
            "failover": has_failover,
            "hedged": has_hedge,
        }
        traces.append(entry)
        if (verdict["orphans"] or verdict["rootless"]) and not verdict["exemplar"]:
            bad_joins.append(tid)

    # Waterfalls for the most interesting traces: every failover/hedge/
    # exemplar first, then the slowest — capped so --json stays bounded.
    def interest(e):
        return (e["failover"] or e["hedged"] or e["exemplar"],
                e["dur_ms"] or 0.0)

    picked = sorted(traces, key=interest, reverse=True)[:max_waterfalls]
    waterfalls = []
    for e in picked:
        group = by_trace[e["trace"]]
        root = _join_trace(group)["root"]
        if root is None:
            continue
        waterfalls.append({
            "trace": e["trace"], "dur_ms": root.get("dur_ms"),
            "coverage": e["coverage"],
            "rows": _waterfall_rows(group, root),
        })

    coverages = [e["coverage"] for e in traces if e["coverage"] is not None]
    # Duration-weighted coverage: share of TOTAL end-to-end latency that
    # the joined span trees explain.  Per-query coverage is noisy for
    # millisecond requests (fixed parse/respond slices loom large); the
    # weighted figure is the fleet-level acceptance number.
    wpairs = [
        (e["coverage"], e["dur_ms"])
        for e in traces
        if e["coverage"] is not None and e["dur_ms"]
    ]
    wtotal = sum(d for _, d in wpairs)
    coverage_weighted = (
        round(sum(c * d for c, d in wpairs) / wtotal, 4) if wtotal else None
    )
    code = 1 if bad_joins else 0
    doc = {
        "dirs": per_dir,
        "spans": len(spans),
        "bad_span_lines": bad,
        "traces": len(traces),
        "joined": len(traces) - len(bad_joins),
        "unjoined_traces": bad_joins,
        "exemplar_traces": sum(1 for e in traces if e["exemplar"]),
        "failover_traces": sum(1 for e in traces if e["failover"]),
        "hedged_traces": sum(1 for e in traces if e["hedged"]),
        "coverage_min": round(min(coverages), 4) if coverages else None,
        "coverage_mean": (
            round(sum(coverages) / len(coverages), 4) if coverages else None
        ),
        "coverage_weighted": coverage_weighted,
        "trace_table": traces,
        "waterfalls": waterfalls,
        "exit": code,
    }
    return doc, code


def render_trace(doc: dict) -> str:
    if "error" in doc:
        return f"TRACE REPORT\n  {doc['error']}"
    lines = ["TRACE REPORT (fleet-wide join)"]
    lines.append(
        f"  dirs {len(doc['dirs'])}  spans {doc['spans']}  "
        f"traces {doc['traces']}  joined {doc['joined']}  "
        f"bad span lines {doc['bad_span_lines']}"
    )
    lines.append(
        f"  failover {doc['failover_traces']}  hedged {doc['hedged_traces']}  "
        f"exemplars {doc['exemplar_traces']}  "
        f"coverage min {doc['coverage_min']} mean {doc['coverage_mean']} "
        f"weighted {doc['coverage_weighted']}"
    )
    if doc["unjoined_traces"]:
        lines.append(
            "  UNJOINED (orphaned/rootless sampled traces): "
            + ", ".join(doc["unjoined_traces"][:10])
        )
    rows = [
        [
            e["trace"][:12], e["root"] or "-", e["spans"],
            _fmt_val_ms(e["dur_ms"]),
            "-" if e["coverage"] is None else f"{e['coverage']:.0%}",
            ",".join(e["services"]),
            "".join([
                "F" if e["failover"] else "",
                "H" if e["hedged"] else "",
                "E" if e["exemplar"] else "",
                "!" if (e["orphans"] or e["rootless"]) else "",
            ]) or "-",
        ]
        for e in doc["trace_table"][:30]
    ]
    lines.append(_table(
        ["trace", "root", "spans", "e2e", "cover", "services", "flags"], rows
    ))
    for wf in doc["waterfalls"]:
        lines.append(
            f"\n  trace {wf['trace']}  {_fmt_val_ms(wf['dur_ms'])}  "
            f"coverage {'-' if wf['coverage'] is None else format(wf['coverage'], '.0%')}"
        )
        for r in wf["rows"]:
            attrs = " ".join(f"{k}={v}" for k, v in r["attrs"].items())
            pad = "  " * r["depth"]
            lines.append(
                f"    {r['offset_ms']:>9.2f}ms {pad}{r['name']} "
                f"[{r['svc']}] {_fmt_val_ms(r['dur_ms'])}"
                + (f"  {attrs}" if attrs else "")
            )
    verdict = "OK" if doc["exit"] == 0 else "JOIN GATE FAILED"
    lines.append(f"\n  {verdict} (exit {doc['exit']})")
    return "\n".join(lines)


def _main_trace(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report trace",
        description="Join trace spans across a router's and its workers' run "
        "dirs into per-query waterfalls; exit 1 when a sampled trace has "
        "orphaned/unjoinable spans, 2 on a bad dir, 3 when no spans exist",
    )
    parser.add_argument("run_dirs", nargs="+",
                        help="run directories (router + every worker)")
    parser.add_argument("--max-waterfalls", type=int, default=5,
                        dest="max_waterfalls",
                        help="waterfall trees to include (default 5)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = trace_doc(args.run_dirs, args.max_waterfalls)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_trace(doc))
    return code


def _dir_slo_ms(run_dir) -> tuple:
    """A run dir's resolved SLO: ``live.json`` ``slo.slo_ms`` (the worker
    wrote its own resolved value there), falling back to the manifest's
    copy; ``(slo_ms, found_live_doc)``."""
    for name in ("live.json", "fleet.json"):
        p = Path(run_dir) / name
        try:
            doc = json.loads(p.read_text())
        except (OSError, ValueError):
            continue
        slo = ((doc.get("slo") or {}).get("slo_ms")
               if isinstance(doc, dict) else None)
        return slo, True
    return None, False


def slo_doc(run_dirs, breach_limit: int = 10) -> tuple:
    """Fleet-wide SLO observatory: per-layer latency breakdowns from trace
    spans, per-dir resolved SLOs, breach exemplar tables, and hedge/failover
    causality for the breached tail.

    Exit codes: 0 ok; 1 when any end-to-end trace breaches its run dir's
    resolved SLO (or carries an ``exemplar`` mark — the writer's own breach
    verdict); 2 bad dir; 3 when neither spans nor any live/fleet snapshot
    exist to judge.
    """
    from sbr_tpu.obs.metrics import DEFAULT_LATENCY_BOUNDS_MS, LogHistogram

    try:
        spans, bad, per_dir = _load_fleet_spans(run_dirs)
    except NotADirectoryError as err:
        return {"error": f"not a run directory: {err}", "exit": 2}, 2

    any_live = False
    for entry in per_dir:
        slo, found = _dir_slo_ms(entry["dir"])
        entry["slo_ms"] = slo
        any_live = any_live or found
    if not spans:
        code = 3 if not any_live else 0
        return {
            "error": "no trace spans recorded (is SBR_TRACE_SAMPLE set?)",
            "dirs": per_dir, "bad_span_lines": bad, "exit": code,
        }, code

    slo_by_dir = {e["dir"]: e["slo_ms"] for e in per_dir}

    # Per-layer duration histograms over every committed span.
    layers: dict = {}
    for s in spans:
        name = s.get("name", "?")
        h = layers.get(name)
        if h is None:
            h = layers[name] = LogHistogram(DEFAULT_LATENCY_BOUNDS_MS)
        h.record(s.get("dur_ms") or 0.0)
    layer_table = {name: h.summary() for name, h in sorted(layers.items())}

    # End-to-end verdict per trace: the root span's duration vs the SLO of
    # the dir that recorded it (each worker may serve under its own SLO).
    by_trace: dict = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    breaches = []
    for tid, group in sorted(by_trace.items()):
        root = _join_trace(group)["root"]
        if root is None:
            continue
        slo = slo_by_dir.get(root.get("_dir"))
        dur = root.get("dur_ms") or 0.0
        marked = any(s.get("exemplar") for s in group)
        if marked or (slo is not None and dur > slo):
            by_layer: dict = {}
            for s in group:
                if s is not root:
                    by_layer[s["name"]] = round(
                        by_layer.get(s["name"], 0.0) + (s.get("dur_ms") or 0.0), 3
                    )
            slowest = max(by_layer.items(), key=lambda kv: kv[1])[0] \
                if by_layer else None
            breaches.append({
                "trace": tid, "dur_ms": dur, "slo_ms": slo,
                "root": root.get("name"), "exemplar": marked,
                "hedged": any(s.get("role") == "hedge" for s in group),
                "failover": any(
                    s.get("name") == "router.forward"
                    and s.get("outcome") == "error"
                    for s in group
                ),
                "degraded": any(s.get("degraded") for s in group),
                "slowest_layer": slowest,
                "by_layer_ms": by_layer,
            })
    breaches.sort(key=lambda b: b["dur_ms"], reverse=True)

    causality = {
        "breaches": len(breaches),
        "hedged": sum(1 for b in breaches if b["hedged"]),
        "failover": sum(1 for b in breaches if b["failover"]),
        "degraded": sum(1 for b in breaches if b["degraded"]),
    }
    code = 1 if breaches else 0
    doc = {
        "dirs": per_dir,
        "spans": len(spans),
        "bad_span_lines": bad,
        "traces": len(by_trace),
        "layers": layer_table,
        "breach_causality": causality,
        "breach_exemplars": breaches[:breach_limit],
        "exit": code,
    }
    return doc, code


def render_slo(doc: dict) -> str:
    if "error" in doc:
        return f"SLO REPORT\n  {doc['error']}"
    lines = ["SLO REPORT (per-layer latency observatory)"]
    for e in doc["dirs"]:
        slo = e.get("slo_ms")
        lines.append(
            f"  {e['dir']}: {e['spans']} span(s), "
            f"slo {'-' if slo is None else f'{slo:g} ms'}"
        )
    rows = [
        [
            name, s["count"], _fmt_val_ms(s.get("p50")),
            _fmt_val_ms(s.get("p95")), _fmt_val_ms(s.get("p99")),
            _fmt_val_ms(s.get("max")),
        ]
        for name, s in doc["layers"].items()
    ]
    lines.append(_table(["layer", "count", "p50", "p95", "p99", "max"], rows))
    c = doc["breach_causality"]
    lines.append(
        f"\n  SLO breaches {c['breaches']}  (hedged {c['hedged']}, "
        f"failover {c['failover']}, degraded {c['degraded']})"
    )
    if doc["breach_exemplars"]:
        rows = [
            [
                b["trace"][:12], _fmt_val_ms(b["dur_ms"]),
                "-" if b["slo_ms"] is None else f"{b['slo_ms']:g}",
                b["slowest_layer"] or "-",
                "".join([
                    "F" if b["failover"] else "",
                    "H" if b["hedged"] else "",
                    "D" if b["degraded"] else "",
                    "E" if b["exemplar"] else "",
                ]) or "-",
            ]
            for b in doc["breach_exemplars"]
        ]
        lines.append(_table(
            ["trace", "e2e", "slo_ms", "slowest layer", "flags"], rows
        ))
    verdict = "OK" if doc["exit"] == 0 else "SLO BREACHED"
    lines.append(f"\n  {verdict} (exit {doc['exit']})")
    return "\n".join(lines)


def _main_slo(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report slo",
        description="Fleet-wide SLO observatory over trace spans: per-layer "
        "latency breakdowns, breach exemplars, hedge/failover causality; "
        "exit 1 on an SLO breach, 2 on a bad dir, 3 with nothing to judge",
    )
    parser.add_argument("run_dirs", nargs="+",
                        help="run directories (router + every worker)")
    parser.add_argument("--breach-limit", type=int, default=10,
                        dest="breach_limit",
                        help="breach exemplar rows to include (default 10)")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = slo_doc(args.run_dirs, args.breach_limit)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_slo(doc))
    return code


# ---------------------------------------------------------------------------
# Meta-gate (`summary` subcommand — ISSUE 20 satellite)
# ---------------------------------------------------------------------------

#: The subgates `report summary` folds, in display order. Each entry maps
#: the gate name to a callable of one run_dir returning (doc, code) —
#: kept lazy (lambdas) so a crashing gate is contained per-row.
_SUMMARY_GATES = (
    ("health", lambda d: health_json(load_run(d))),
    ("serve", lambda d: serve_doc(d)),
    ("fleet", lambda d: fleet_doc(d)),
    ("trace", lambda d: trace_doc([d])),
    ("slo", lambda d: slo_doc([d])),
    ("audit", lambda d: audit_doc(d)),
    ("demand", lambda d: demand_doc([d])),
    ("prewarm", lambda d: prewarm_doc(d)),
    ("util", lambda d: util_doc(d)),
)


def _gate_reason(doc, code: int) -> str:
    """One-line reason for a subgate row: 'ok' on 0, else the first
    breach/error the gate reported (truncated for the table)."""
    if code == 0:
        return "ok"
    reason = None
    if isinstance(doc, dict):
        for key in ("breaches", "reasons"):
            vals = doc.get(key)
            if vals:
                reason = str(vals[0])
                break
        if reason is None and doc.get("error"):
            reason = str(doc["error"])
        if reason is None and code == 1 and doc.get("total_divergent"):
            reason = f"{doc['total_divergent']} divergent cell(s)"
    if reason is None:
        reason = f"exit {code}"
    return reason if len(reason) <= 90 else reason[:87] + "..."


def summary_doc(run_dir) -> tuple:
    """The meta-gate (ISSUE 20 satellite): every observatory gate —
    health, serve, fleet, trace, slo, audit, demand, prewarm, util — run
    against ONE run dir, folded into a single table. Returns
    (doc, exit_code) where the merged exit is the MAX of the subgate
    exits (so a single breach (1) outranks ok (0), and a bad dir (2) /
    no-data (3) surfaces as itself — observatories that simply were not
    enabled show their honest 3 rather than silently passing). A subgate
    that CRASHES reads as exit 2 with the error as its reason: a broken
    gate must not read as clean."""
    run_dir = Path(run_dir)
    if not run_dir.is_dir():
        return {"dir": str(run_dir), "error": "not a directory", "exit": 2}, 2
    gates = {}
    for name, fn in _SUMMARY_GATES:
        try:
            doc, code = fn(str(run_dir))
        except Exception as err:
            doc, code = {"error": f"{type(err).__name__}: {err}"}, 2
        gates[name] = {"exit": code, "reason": _gate_reason(doc, code)}
    merged = max(g["exit"] for g in gates.values())
    doc = {
        "dir": str(run_dir),
        "gates": gates,
        "exit": merged,
    }
    return doc, merged


def render_summary(doc: dict) -> str:
    """Human-readable meta-gate table; same exit contract as `summary_doc`."""
    out = [f"run      {doc['dir']}"]
    if "gates" not in doc:
        out.append(doc.get("error", "no data"))
        return "\n".join(out)
    out += ["", "GATES"]
    out.append(_table(
        ["gate", "exit", "reason"],
        [[name, g["exit"], g["reason"]]
         for name, g in doc["gates"].items()],
    ))
    out.append("")
    worst = doc["exit"]
    if worst == 0:
        out.append("GATE: ok (every subgate passed)")
    else:
        failing = [n for n, g in doc["gates"].items() if g["exit"] == worst]
        out.append(
            f"GATE: exit {worst} (worst subgate(s): {', '.join(failing)})"
        )
    return "\n".join(out)


def _main_summary(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report summary",
        description="Meta-gate: run every observatory gate (health, serve, "
        "fleet, trace, slo, audit, demand, prewarm, util) against one run "
        "dir and fold them into a single table; the merged exit code is "
        "the max of the subgate exits",
    )
    parser.add_argument("run_dir", help="obs run directory")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)
    doc, code = summary_doc(args.run_dir)
    if args.json:
        print(json.dumps(doc, default=str))
        return code
    print(render_summary(doc))
    return code


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Subcommand dispatch; a bare run-dir path keeps the legacy render/diff
    # interface (a directory named "health"/"gc" can be reached as ./health).
    if argv and argv[0] == "health":
        return _main_health(argv[1:])
    if argv and argv[0] == "resilience":
        return _main_resilience(argv[1:])
    if argv and argv[0] == "memory":
        return _main_memory(argv[1:])
    if argv and argv[0] == "elastic":
        return _main_elastic(argv[1:])
    if argv and argv[0] == "serve":
        return _main_serve(argv[1:])
    if argv and argv[0] == "fleet":
        return _main_fleet(argv[1:])
    if argv and argv[0] == "audit":
        return _main_audit(argv[1:])
    if argv and argv[0] == "demand":
        return _main_demand(argv[1:])
    if argv and argv[0] == "prewarm":
        return _main_prewarm(argv[1:])
    if argv and argv[0] == "util":
        return _main_util(argv[1:])
    if argv and argv[0] == "summary":
        return _main_summary(argv[1:])
    if argv and argv[0] == "grad":
        return _main_grad(argv[1:])
    if argv and argv[0] == "infomodel":
        return _main_infomodel(argv[1:])
    if argv and argv[0] == "trace":
        return _main_trace(argv[1:])
    if argv and argv[0] == "slo":
        return _main_slo(argv[1:])
    if argv and argv[0] == "gc":
        return _main_gc(argv[1:])
    if argv and argv[0] == "trend":
        # Perf-history trend/regression gate — jax-free, like this module.
        from sbr_tpu.obs.history import main_trend

        return main_trend(argv[1:])

    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report",
        description="Render an obs run directory, diff two runs, or run the "
        "'health' / 'resilience' / 'memory' / 'elastic' / 'serve' / 'fleet' / "
        "'audit' / 'demand' / 'prewarm' / 'util' / 'summary' / 'grad' / "
        "'infomodel' / 'trace' / 'slo' / 'trend' / 'gc' subcommands",
    )
    parser.add_argument("run_dir", help="run directory (contains manifest.json)")
    parser.add_argument("other_dir", nargs="?", help="second run directory to diff against")
    parser.add_argument("--events", type=int, default=0, metavar="N", help="also print the last N raw events")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)

    try:
        run = load_run(args.run_dir)
    except (FileNotFoundError, json.JSONDecodeError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    if args.other_dir:
        try:
            other = load_run(args.other_dir)
        except (FileNotFoundError, json.JSONDecodeError) as err:
            print(f"error: {err}", file=sys.stderr)
            return 1
        print(json.dumps(diff_json(run, other), default=str) if args.json else diff(run, other))
    else:
        if args.json:
            print(json.dumps(render_json(run), default=str))
            return 0
        print(render(run))
        if args.events:
            print(f"\nLAST {args.events} EVENTS")
            for ev in run["events"][-args.events :]:
                print(f"  {ev.get('mono', 0):>12.6f}  {ev.get('kind', '?'):<12} "
                      + " ".join(f"{k}={v}" for k, v in ev.items() if k not in ("mono", "ts", "kind")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
