"""Metrics registry: counters, gauges, and timer histograms.

Jit-safety contract (SURVEY §5.1, torchode-style step statistics): metrics
are only ever recorded from HOST code — either at the host boundary from
returned arrays (status codes, iteration counts, residuals) or as
trace-time counters (host Python that runs while a program is being traced,
counting traced solver instances without touching the computation graph).
Nothing here may appear inside traced code, so enabling or disabling
metrics can never change a jaxpr or force a retrace.

Overhead contract: every recording method starts with a single attribute
test and returns immediately when the registry is disabled, so dormant
instrumentation in hot host loops (tile drivers, graph preprocessing) costs
one branch per call and allocates nothing.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional, Tuple


def log_bounds(lo: float, hi: float, per_decade: int = 4) -> Tuple[float, ...]:
    """Geometric bucket boundaries from ``lo`` to at least ``hi`` with
    ``per_decade`` buckets per decade — the shared shape for latency
    histograms (serve live metrics, timer summaries)."""
    step = 10.0 ** (1.0 / per_decade)
    bounds = [lo]
    while bounds[-1] < hi:
        bounds.append(bounds[-1] * step)
    return tuple(bounds)


# The ONE latency bucket family (ms): 0.05 ms .. ~2 min. Serve's window
# histograms and the registry's default value histograms share it, so
# `LogHistogram.add`/`delta` can always fold across the two and quantiles
# stay comparable.
DEFAULT_LATENCY_BOUNDS_MS = log_bounds(0.05, 120_000.0, per_decade=4)


class LogHistogram:
    """Fixed-boundary histogram with O(1) record and derivable quantiles.

    ``bounds`` are ascending upper edges; values above the last edge land
    in an overflow bucket. Recording is append-free (one list-index
    increment), so a histogram shared across threads needs no lock under
    CPython — increments of an int slot are effectively atomic at this
    granularity, and the worst race drops one count from a *window*
    aggregate, never corrupts state. Quantiles interpolate within the
    winning bucket (log-bucketed bounds ⇒ bounded relative error), which is
    exactly the Prometheus histogram contract — `to_prometheus` renders the
    cumulative ``le`` form."""

    __slots__ = ("bounds", "counts", "count", "total", "max")

    def __init__(self, bounds: Tuple[float, ...]) -> None:
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +1 overflow
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def record(self, value: float) -> None:
        v = float(value)
        lo, hi = 0, len(self.bounds)
        while lo < hi:  # first bound >= v (bisect, inlined: hot path)
            mid = (lo + hi) // 2
            if self.bounds[mid] < v:
                lo = mid + 1
            else:
                hi = mid
        self.counts[lo] += 1
        self.count += 1
        self.total += v
        if v > self.max:
            self.max = v

    def add(self, other: "LogHistogram") -> None:
        """Fold ``other`` (same bounds) into this histogram — the window
        aggregation step. Bounds mismatch is a programming error."""
        if other.bounds != self.bounds:
            raise ValueError("LogHistogram.add: bucket bounds differ")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        if other.max > self.max:
            self.max = other.max

    def delta(self, before: "LogHistogram") -> "LogHistogram":
        """New histogram holding the samples recorded since ``before`` (a
        prior snapshot of this histogram with the same bounds) — the
        phase-isolation counterpart of `add` (e.g. a bench's measured-phase
        quantiles must exclude warmup samples). ``max`` carries this
        histogram's lifetime max: an exact delta max is unknowable from
        bucket counts, and only the overflow bucket's quantile reads it —
        an UPPER bound for the phase, never an undershoot."""
        if before.bounds != self.bounds:
            raise ValueError("LogHistogram.delta: bucket bounds differ")
        out = LogHistogram(self.bounds)
        out.counts = [a - b for a, b in zip(self.counts, before.counts)]
        out.count = self.count - before.count
        out.total = self.total - before.total
        out.max = self.max
        return out

    def copy(self) -> "LogHistogram":
        out = LogHistogram(self.bounds)
        out.counts = list(self.counts)
        out.count = self.count
        out.total = self.total
        out.max = self.max
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile (0..1) from the buckets; None when empty.
        Interpolates linearly inside the winning bucket; the overflow
        bucket reports the observed max."""
        if self.count == 0:
            return None
        target = q * self.count
        seen = 0.0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= target:
                if i >= len(self.bounds):  # overflow bucket
                    return self.max
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (target - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.max

    def summary(self) -> dict:
        """JSON-ready reduction: count/sum/max plus p50/p95/p99."""
        out = {
            "count": self.count,
            "sum": round(self.total, 6),
            "max": round(self.max, 6),
        }
        for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            v = self.quantile(q)
            out[name] = None if v is None else round(v, 6)
        return out

    def to_prometheus(self, name: str, labels: str = "") -> List[str]:
        """Cumulative ``le``-labeled Prometheus text lines for this
        histogram (``labels`` is a pre-rendered ``k="v",...`` fragment)."""
        sep = "," if labels else ""
        lines = [f"# TYPE {name} histogram"]
        cum = 0
        for bound, c in zip(self.bounds, self.counts):
            cum += c
            lines.append(f'{name}_bucket{{{labels}{sep}le="{bound:g}"}} {cum}')
        cum += self.counts[-1]
        lines.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {cum}')
        brace = f"{{{labels}}}" if labels else ""
        lines.append(f"{name}_sum{brace} {self.total:g}")
        lines.append(f"{name}_count{brace} {self.count}")
        return lines

    def to_dict(self) -> dict:
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "count": self.count, "sum": self.total, "max": self.max}

    @classmethod
    def from_dict(cls, doc: dict) -> "LogHistogram":
        h = cls(tuple(doc.get("bounds") or (1.0,)))
        counts = list(doc.get("counts") or [])
        if len(counts) == len(h.counts):
            h.counts = [int(c) for c in counts]
        h.count = int(doc.get("count", sum(h.counts)))
        h.total = float(doc.get("sum", 0.0))
        h.max = float(doc.get("max", 0.0))
        return h


class LabeledHistograms:
    """A family of `LogHistogram`s keyed by one label value.

    The shape behind per-layer span-duration histograms on ``/metrics``
    (``sbr_trace_span_ms{layer="engine.dispatch"}``): `record` is the same
    O(1) lock-free-under-CPython increment `LogHistogram.record` is — the
    dict-get worst race creates one extra throwaway histogram whose single
    sample is lost, never corrupts existing buckets. ``max_labels`` bounds
    cardinality: past it, new labels fold into ``"other"`` instead of
    growing the exposition without bound."""

    __slots__ = ("bounds", "by_label", "max_labels")

    def __init__(self, bounds: Tuple[float, ...], max_labels: int = 64) -> None:
        self.bounds = tuple(bounds)
        self.by_label: Dict[str, LogHistogram] = {}
        self.max_labels = max_labels

    def record(self, label: str, value: float) -> None:
        h = self.by_label.get(label)
        if h is None:
            if len(self.by_label) >= self.max_labels:
                label = "other"
                h = self.by_label.get(label)
            if h is None:
                h = self.by_label.setdefault(label, LogHistogram(self.bounds))
        h.record(value)

    def summaries(self) -> Dict[str, dict]:
        """JSON-ready per-label reductions, label-sorted for determinism."""
        return {k: self.by_label[k].summary() for k in sorted(self.by_label)}

    def to_prometheus(self, name: str, label_key: str = "layer") -> List[str]:
        """Exposition lines for every label's histogram under one family.

        Emits the ``# TYPE`` header once; per-label lines carry
        ``label_key="<label>"`` so a single scrape shows the full per-layer
        latency breakdown."""
        if not self.by_label:
            return []
        lines = [f"# TYPE {name} histogram"]
        for label in sorted(self.by_label):
            sub = self.by_label[label].to_prometheus(
                name, labels=f'{label_key}="{label}"'
            )
            lines.extend(sub[1:])  # drop the per-label TYPE header
        return lines


class MetricsRegistry:
    """Process-local counters / gauges / timer histograms.

    Disabled by default; `RunContext` enables it for the run's duration and
    folds `summary()` into the run manifest. All recording methods are
    no-ops while disabled (see module docstring for the overhead contract).
    """

    __slots__ = ("_on", "counters", "gauges", "timers", "hists")

    def __init__(self) -> None:
        self._on = False
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, List[float]] = {}
        self.hists: Dict[str, LogHistogram] = {}

    # -- lifecycle ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._on

    def enable(self) -> None:
        self._on = True

    def disable(self) -> None:
        self._on = False

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.timers.clear()
        self.hists.clear()

    # -- recording (all no-ops while disabled) ------------------------------
    def inc(self, name: str, n: float = 1) -> None:
        """Increment counter ``name`` by ``n``."""
        if not self._on:
            return
        self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        """Set gauge ``name`` to its latest ``value``."""
        if not self._on:
            return
        self.gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        """Record one duration sample into timer histogram ``name``."""
        if not self._on:
            return
        self.timers.setdefault(name, []).append(float(seconds))

    _DEFAULT_HIST_BOUNDS = DEFAULT_LATENCY_BOUNDS_MS

    def observe_value(self, name: str, value: float,
                      bounds: Optional[Tuple[float, ...]] = None) -> None:
        """Record one sample into the log-bucketed value histogram ``name``
        (created on first use; default bounds cover 0.05 ms .. 2 min —
        the serving latency shape). Unlike `observe`, memory is O(buckets)
        however many samples land, so hot query paths can record every
        event without growing a list."""
        if not self._on:
            return
        h = self.hists.get(name)
        if h is None:
            h = self.hists[name] = LogHistogram(bounds or self._DEFAULT_HIST_BOUNDS)
        h.record(value)

    @contextlib.contextmanager
    def timer(self, name: str):
        """Time the enclosed block into histogram ``name`` (host wall-clock;
        callers timing device work should fence first — see obs.timing)."""
        if not self._on:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # -- reporting ----------------------------------------------------------
    def summary(self) -> dict:
        """JSON-ready snapshot: counters/gauges verbatim, timers reduced to
        count/total/min/mean/p50/p95/max (keys sorted for determinism)."""

        def _hist(samples: List[float]) -> dict:
            s = sorted(samples)
            n = len(s)
            return {
                "count": n,
                "total_s": sum(s),
                "min_s": s[0],
                "mean_s": sum(s) / n,
                "p50_s": s[n // 2],
                "p95_s": s[min(n - 1, (19 * n) // 20)],
                "max_s": s[-1],
            }

        out = {
            "counters": {k: self.counters[k] for k in sorted(self.counters)},
            "gauges": {k: self.gauges[k] for k in sorted(self.gauges)},
            "timers": {k: _hist(v) for k, v in sorted(self.timers.items())},
        }
        if self.hists:
            out["hists"] = {k: self.hists[k].summary() for k in sorted(self.hists)}
        return out


_GLOBAL = MetricsRegistry()


def metrics() -> MetricsRegistry:
    """The process-global registry (instrumentation call sites and tests)."""
    return _GLOBAL
