"""Metrics registry: counters, gauges, and timer histograms.

Jit-safety contract (SURVEY §5.1, torchode-style step statistics): metrics
are only ever recorded from HOST code — either at the host boundary from
returned arrays (status codes, iteration counts, residuals) or as
trace-time counters (host Python that runs while a program is being traced,
counting traced solver instances without touching the computation graph).
Nothing here may appear inside traced code, so enabling or disabling
metrics can never change a jaxpr or force a retrace.

Overhead contract: every recording method starts with a single attribute
test and returns immediately when the registry is disabled, so dormant
instrumentation in hot host loops (tile drivers, graph preprocessing) costs
one branch per call and allocates nothing.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List


class MetricsRegistry:
    """Process-local counters / gauges / timer histograms.

    Disabled by default; `RunContext` enables it for the run's duration and
    folds `summary()` into the run manifest. All recording methods are
    no-ops while disabled (see module docstring for the overhead contract).
    """

    __slots__ = ("_on", "counters", "gauges", "timers")

    def __init__(self) -> None:
        self._on = False
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, List[float]] = {}

    # -- lifecycle ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._on

    def enable(self) -> None:
        self._on = True

    def disable(self) -> None:
        self._on = False

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.timers.clear()

    # -- recording (all no-ops while disabled) ------------------------------
    def inc(self, name: str, n: float = 1) -> None:
        """Increment counter ``name`` by ``n``."""
        if not self._on:
            return
        self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        """Set gauge ``name`` to its latest ``value``."""
        if not self._on:
            return
        self.gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        """Record one duration sample into timer histogram ``name``."""
        if not self._on:
            return
        self.timers.setdefault(name, []).append(float(seconds))

    @contextlib.contextmanager
    def timer(self, name: str):
        """Time the enclosed block into histogram ``name`` (host wall-clock;
        callers timing device work should fence first — see obs.timing)."""
        if not self._on:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # -- reporting ----------------------------------------------------------
    def summary(self) -> dict:
        """JSON-ready snapshot: counters/gauges verbatim, timers reduced to
        count/total/min/mean/p50/p95/max (keys sorted for determinism)."""

        def _hist(samples: List[float]) -> dict:
            s = sorted(samples)
            n = len(s)
            return {
                "count": n,
                "total_s": sum(s),
                "min_s": s[0],
                "mean_s": sum(s) / n,
                "p50_s": s[n // 2],
                "p95_s": s[min(n - 1, (19 * n) // 20)],
                "max_s": s[-1],
            }

        return {
            "counters": {k: self.counters[k] for k in sorted(self.counters)},
            "gauges": {k: self.gauges[k] for k in sorted(self.gauges)},
            "timers": {k: _hist(v) for k, v in sorted(self.timers.items())},
        }


_GLOBAL = MetricsRegistry()


def metrics() -> MetricsRegistry:
    """The process-global registry (instrumentation call sites and tests)."""
    return _GLOBAL
