"""Compile/retrace tracking and profiler capture — the performance-
observatory layer of the ``obs`` telemetry subsystem (ISSUE 3 tentpole).

Three concerns, all host-side and all zero-overhead when dormant:

- **Global compile tracking** (`install`): `jax.monitoring` duration
  listeners fold every XLA compile phase (jaxpr trace, MLIR lowering,
  backend compile) into process totals and — when a run is active — into
  the run's ``xla`` manifest block, attributed to the innermost open
  `obs.span`. Listeners are process-global and cannot be removed per-run
  (``clear_event_listeners`` would nuke jax's own), so they install once
  and route to ``runlog.active_run()`` at fire time. On jax builds without
  `jax.monitoring` everything degrades to a no-op (`monitoring_available`
  reports it, the manifest says so).
- **Retrace registry** (`note_trace`): a per-jitted-function trace counter.
  Call it at the top of the Python body of a function about to be
  ``jax.jit``-ed: the body runs once per TRACE (a shape/dtype/static-arg
  cache miss) and never at execute time, so the count is exactly jit's
  miss count for that name — and, being pure host Python, it cannot change
  the traced computation (asserted by tests/test_prof.py). When a run is
  active and the within-run count exceeds the name's budget, a ``retrace``
  warning event lands in the log: the signature of argument shape/dtype
  churn silently recompiling a hot program.
- **Profiler capture** (`profile`): a context manager around
  ``jax.profiler.trace`` gated on ``SBR_OBS_PROFILE=1`` (or ``force=``).
  The trace directory lives INSIDE the active run directory, so the
  existing retention machinery (`report gc` / ``SBR_OBS_KEEP``) prunes
  captures with their runs; a capture larger than
  ``SBR_OBS_PROFILE_MAX_MB`` (default 256) is deleted on the spot and
  recorded as pruned. A compact host-side summary (path, file count,
  bytes, capture window) is emitted as a ``profile`` event and folded into
  the manifest. `annotate`/`step_annotation` wrap solver stages and bench
  reps in ``jax.profiler.TraceAnnotation``/``StepTraceAnnotation`` so the
  xplane timeline carries the pipeline's stage names — both are no-ops
  unless profiling is enabled, so the default path stays untouched.

Nothing in this module imports jax at module scope: the bench parent and
the report CLI can import it without waking an accelerator backend.
"""

from __future__ import annotations

import contextlib
import os
import time
from pathlib import Path
from typing import Optional

# Map of the jax.monitoring duration events we fold -> manifest keys.
_COMPILE_EVENTS = {
    "/jax/core/compile/jaxpr_trace_duration": "jaxpr_trace_s",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "mlir_lowering_s",
    "/jax/core/compile/backend_compile_duration": "backend_compile_s",
}

_INSTALLED = False
_MONITORING_OK: Optional[bool] = None
# Process-lifetime totals (runs report deltas via their own aggregates).
_TOTALS = {
    "compiles": 0,
    "jaxpr_trace_s": 0.0,
    "mlir_lowering_s": 0.0,
    "backend_compile_s": 0.0,
}

# Per-jitted-function trace counts (process-lifetime; runs snapshot at start
# and report deltas) and per-name retrace budgets.
_TRACE_COUNTS: dict = {}
_TRACE_BUDGETS: dict = {}


# ---------------------------------------------------------------------------
# Compile tracking (jax.monitoring listeners)
# ---------------------------------------------------------------------------


def _on_compile_duration(event: str, duration: float, **kw) -> None:
    """Duration listener: fires on every XLA compile phase in the process.
    Must never raise (jax would surface it mid-compile) and must be cheap
    when no run is active — two dict ops."""
    key = _COMPILE_EVENTS.get(event)
    if key is None:
        return
    _TOTALS[key] += duration
    if key == "backend_compile_s":
        _TOTALS["compiles"] += 1
    try:
        from sbr_tpu.obs import runlog

        run = runlog.active_run()  # never auto-starts from the env
        if run is not None:
            run._note_xla(key, float(duration), runlog.active_span())
    except Exception:
        pass


def install() -> bool:
    """Register the compile listeners once per process (idempotent).
    Returns whether `jax.monitoring` is available; on jax builds without it
    the observatory degrades gracefully to span/jit_call timing only."""
    global _INSTALLED, _MONITORING_OK
    if _INSTALLED:
        return bool(_MONITORING_OK)
    _INSTALLED = True
    try:
        import jax.monitoring as monitoring

        monitoring.register_event_duration_secs_listener(_on_compile_duration)
        _MONITORING_OK = True
    except Exception:
        _MONITORING_OK = False
    return bool(_MONITORING_OK)


def monitoring_available() -> bool:
    """True when the jax.monitoring listeners are installed and live."""
    return bool(_MONITORING_OK) if _INSTALLED else False


def compile_totals() -> dict:
    """Process-lifetime XLA compile totals folded by the listeners."""
    return dict(_TOTALS)


# ---------------------------------------------------------------------------
# Retrace registry
# ---------------------------------------------------------------------------


def _default_budget() -> int:
    env = os.environ.get("SBR_OBS_RETRACE_BUDGET", "").strip()
    return int(env) if env else 3


def trace_budget(name: str) -> int:
    return _TRACE_BUDGETS.get(name, _default_budget())


def note_trace(name: str, budget: Optional[int] = None) -> int:
    """Record one TRACE of the named jitted program; returns the new
    process-lifetime count. Call sites place this at the top of the Python
    body handed to ``jax.jit`` — see the module docstring for why that is
    exactly a trace counter and can never perturb the computation. A
    ``budget`` given here sticks for the name (first writer wins per call,
    last writer overall)."""
    n = _TRACE_COUNTS.get(name, 0) + 1
    _TRACE_COUNTS[name] = n
    if budget is not None:
        _TRACE_BUDGETS[name] = int(budget)
    try:
        from sbr_tpu.obs import runlog

        run = runlog.active_run()
        if run is not None:
            run._note_trace(name, n)
    except Exception:
        pass
    return n


def trace_counts() -> dict:
    """Snapshot of the per-name process-lifetime trace counts."""
    return dict(_TRACE_COUNTS)


def reset_trace_counts() -> None:
    """Test hook: forget all counts and budgets."""
    _TRACE_COUNTS.clear()
    _TRACE_BUDGETS.clear()


# ---------------------------------------------------------------------------
# Profiler capture + annotations
# ---------------------------------------------------------------------------


def profiling_enabled() -> bool:
    """Opt-in flag for profiler capture and annotations (SBR_OBS_PROFILE=1).
    Read per call — cheap, and tests/one-off shells can toggle it live."""
    return os.environ.get("SBR_OBS_PROFILE", "").strip() not in ("", "0")


def _profile_budget_bytes() -> int:
    env = os.environ.get("SBR_OBS_PROFILE_MAX_MB", "").strip()
    return int(float(env) * 1024 * 1024) if env else 256 * 1024 * 1024


def _summarize_dir(d: Path) -> dict:
    files = 0
    total = 0
    try:
        for p in d.rglob("*"):
            if p.is_file():
                files += 1
                total += p.stat().st_size
    except OSError:
        pass
    return {"files": files, "bytes": total}


@contextlib.contextmanager
def annotate(name: str):
    """``jax.profiler.TraceAnnotation`` around a host-side stage — the
    xplane timeline then carries the pipeline's span names. No-op (and
    jax-import-free) unless profiling is enabled."""
    if not profiling_enabled():
        yield
        return
    try:
        from jax.profiler import TraceAnnotation
    except Exception:
        yield
        return
    with TraceAnnotation(name):
        yield


@contextlib.contextmanager
def step_annotation(step: int, name: str = "step"):
    """``jax.profiler.StepTraceAnnotation`` for per-rep/step framing in
    bench loops. No-op unless profiling is enabled."""
    if not profiling_enabled():
        yield
        return
    try:
        from jax.profiler import StepTraceAnnotation
    except Exception:
        yield
        return
    with StepTraceAnnotation(name, step_num=int(step)):
        yield


@contextlib.contextmanager
def profile(label: str = "capture", force: bool = False):
    """Capture a size-bounded ``jax.profiler.trace`` for the enclosed block.

    Yields the trace directory (a Path) while capturing, or None when
    profiling is off (``SBR_OBS_PROFILE`` unset and not ``force``) or the
    profiler is unavailable — callers use that to skip profile-only work::

        with obs.profile("bench.grid") as trace_dir:
            if trace_dir is not None:
                run_one_rep()

    The directory lands inside the active run dir (``<run>/profile/``), so
    run retention prunes captures with their runs; with no run active it
    falls back to ``SBR_OBS_PROFILE_DIR`` (default ``obs_profile/``). A
    compact summary (path, files, bytes, window) is emitted as a
    ``profile`` event and folded into the manifest; captures exceeding
    ``SBR_OBS_PROFILE_MAX_MB`` are deleted and recorded as pruned.
    """
    if not (force or profiling_enabled()):
        yield None
        return
    from sbr_tpu.obs import runlog

    run = runlog.active_run()
    root = (
        run.run_dir / "profile"
        if run is not None
        else Path(os.environ.get("SBR_OBS_PROFILE_DIR", "obs_profile"))
    )
    trace_dir = root / f"{label.replace('/', '_')}_{time.strftime('%Y%m%dT%H%M%S')}"
    i = 0
    while trace_dir.exists():
        i += 1
        trace_dir = Path(f"{trace_dir}_{i}")
    try:
        import jax.profiler

        ctx = jax.profiler.trace(str(trace_dir))
    except Exception as err:  # profiler unavailable: never sink the caller
        if run is not None:
            run.event("profile", label=label, error=repr(err))
        yield None
        return
    t0 = time.monotonic()
    started_at = time.strftime("%Y-%m-%dT%H:%M:%S")
    try:
        with ctx:
            yield trace_dir
    finally:
        window_s = time.monotonic() - t0
        summary = _summarize_dir(trace_dir)
        budget = _profile_budget_bytes()
        pruned = summary["bytes"] > budget
        if pruned:
            import shutil

            shutil.rmtree(trace_dir, ignore_errors=True)
        rec = {
            "label": label,
            "trace_dir": str(trace_dir),
            "files": summary["files"],
            "bytes": summary["bytes"],
            "pruned": pruned,
            "max_bytes": budget,
            "window_s": round(window_s, 6),
            "started_at": started_at,
        }
        # `run` was resolved at entry: the capture is attributed to the run
        # that owned it even if the block suspended telemetry inside.
        if run is not None:
            run.event("profile", **rec)
            run.profiles.append(rec)
