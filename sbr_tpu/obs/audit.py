"""Numerics audit observatory (ISSUE 17): one golden-surface registry,
one canary runner, four legacy parity CLIs behind one protocol.

The paper's equilibrium selection is numerically delicate — Stage 3
rejects false equilibria on a finite-difference slope check, and a
silently drifted hazard crossing flips a cell from NO_RUN to RUN — yet
the repo's bitwise/ulp/tolerance contracts historically lived in four
scattered CI-time batteries. This module unifies them:

- a versioned **golden-surface registry**: content-addressed expected
  fingerprints of small canonical solve surfaces, keyed per environment
  (platform, x64 mode, jax version, program versions). Golden files are
  JSON (`goldens_<keyhash>.json`) under ``SBR_AUDIT_REGISTRY_DIR``
  (default ``~/.cache/sbr_tpu/audit_goldens``), stamped with
  ``AUDIT_REGISTRY_VERSION`` and refused LOUDLY on a version mismatch
  (regeneration hint included) — a silently tolerated stale golden is a
  green light on drifted math.
- a **canary runner** (`run_battery`) that executes the probe battery,
  classifies each probe against the registry at its documented contract
  tier, emits ``audit`` obs events + a manifest roll-up, and writes a
  per-cycle artifact (``audit/battery_NNNN.json``) into the active run
  dir. ``python -m sbr_tpu.obs.audit`` is the single CLI entry; exit 0
  pass / 1 drift / 2 registry-version or usage error / 3 no goldens.
- an **AuditScheduler** serve workers run off the hot path
  (``SBR_AUDIT_INTERVAL_S``, engine-idle aware — a cycle defers while
  queries are inflight or queued, never inside a batch window). Status
  and last-pass timestamp ride heartbeats, ``/statz`` and ``/metrics``
  (``sbr_audit_status``, per-probe ``sbr_audit_probe_ms`` histograms); a
  drift verdict latches, flips ``/healthz`` degraded with an
  ``audit_drift`` reason, and the router quarantines the worker like an
  open breaker — numerical corruption degrades capacity, not
  correctness.

Probe matrix (contract tier per probe):

=====================  =========  =============================================
probe                  tier       canonical surface
=====================  =========  =============================================
``grid.baseline``      bitwise    default-params baseline equilibrium solve
``grid.hetero``        bitwise    two-group hetero equilibrium (Figure-9 shape)
``grid.interest``      bitwise    interest-rate equilibrium (r=0.06, δ=0.1)
``grid.social``        ulp        social fixed point (Figure-12 params); the
                                  damped iteration tolerates last-ulp libm
                                  variation, so values match to ≤ ``max_ulps``
``scenario.composed``  bitwise    6×6 composed grid (insurance_cap + lolr)
``infomodel.gossip``   bitwise    static gossip trajectory on a seeded ER graph
``graphgen.layout``    bitwise    canonical dst-sorted device layout hash
``grad.ift_fd``        tolerance  IFT-vs-central-FD worst relative error (f64
                                  only — skipped when x64 is off)
=====================  =========  =============================================

``SBR_AUDIT=0`` (the default outside serve/CI) is a strict structural
no-op: the scheduler is never constructed, no probe ever traces, and
`sbr_tpu.obs.prof` trace counters witness zero new XLA programs.

Fault injection: every probe execution fires the ``audit.canary`` fault
point (`resilience.faults`) with the probe name as target — a ``nan`` or
``corrupt`` rule perturbs the canary RESULT pre-comparison (never the
serving path), so drift detection itself is chaos-testable
(``python -m sbr_tpu.resilience.chaos --audit``).

This module is deliberately jax-free at import time (like `obs.report`
and `resilience.chaos`): probes import their stacks lazily, so the
jax-free drivers can import the registry machinery.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import sys
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Callable, Optional

AUDIT_REGISTRY_VERSION = 1
TIERS = ("bitwise", "ulp", "tolerance")
DEFAULT_INTERVAL_S = 300.0
_GOLDEN_PREFIX = "goldens_"
_ARTIFACT_DIR = "audit"


class AuditRegistryVersionError(RuntimeError):
    """A golden file written under a different AUDIT_REGISTRY_VERSION.

    Raised LOUDLY (never silently passed): the classification semantics a
    golden was captured under may have changed, so comparing against it
    proves nothing. The message carries the regeneration hint."""


# ---------------------------------------------------------------------------
# Environment knobs
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """``SBR_AUDIT`` opt-in; empty or "0" (the default) means fully off."""
    return os.environ.get("SBR_AUDIT", "").strip() not in ("", "0")


def interval_s() -> float:
    """Scheduled canary cadence (``SBR_AUDIT_INTERVAL_S``, default 300)."""
    raw = os.environ.get("SBR_AUDIT_INTERVAL_S", "").strip()
    try:
        return float(raw) if raw else DEFAULT_INTERVAL_S
    except ValueError:
        return DEFAULT_INTERVAL_S


def registry_dir() -> Path:
    """Golden registry root (``SBR_AUDIT_REGISTRY_DIR`` or the user cache)."""
    raw = os.environ.get("SBR_AUDIT_REGISTRY_DIR", "").strip()
    if raw:
        return Path(raw)
    return Path.home() / ".cache" / "sbr_tpu" / "audit_goldens"


def probe_filter() -> Optional[tuple]:
    """``SBR_AUDIT_PROBES`` csv restriction (None = full battery)."""
    raw = os.environ.get("SBR_AUDIT_PROBES", "").strip()
    if not raw:
        return None
    names = tuple(p.strip() for p in raw.split(",") if p.strip())
    return names or None


# ---------------------------------------------------------------------------
# Probe protocol + registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Probe:
    """One canonical solve surface and its classification contract.

    ``fn`` returns ``{"fingerprint": sha256-hex, "values": {name: float},
    "meta": {...}}`` (and optionally ``"ok": bool`` for tolerance-tier
    internal self-checks). The fingerprint covers the FULL host-converted
    result payload; ``values`` are the scalar summaries the ulp/tolerance
    tiers compare."""

    name: str
    tier: str
    fn: Callable[[], dict]
    max_ulps: int = 4
    tol: float = 1e-5
    requires_x64: bool = False
    doc: str = ""

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(f"probe {self.name!r}: tier must be one of {TIERS}")


_PROBES: "OrderedDict[str, Probe]" = OrderedDict()
_BUILTINS_REGISTERED = False


def register_probe(
    name: str,
    tier: str,
    fn: Callable[[], dict],
    *,
    max_ulps: int = 4,
    tol: float = 1e-5,
    requires_x64: bool = False,
    doc: str = "",
) -> Probe:
    """Register (or replace) a probe in the process-global battery."""
    p = Probe(name=name, tier=tier, fn=fn, max_ulps=max_ulps, tol=tol,
              requires_x64=requires_x64, doc=doc)
    _PROBES[name] = p
    return p


def probes() -> "OrderedDict[str, Probe]":
    """The full battery (built-ins registered on first call)."""
    _ensure_builtin_probes()
    return _PROBES


# ---------------------------------------------------------------------------
# Fingerprint helpers
# ---------------------------------------------------------------------------


def _to_host(obj):
    """Recursively convert a solver result (nested dataclasses of jax
    arrays) into a canonicalize-able host structure. Wall-clock fields
    (``solve_time``) are excluded — a fingerprint must depend only on
    math, never on the stopwatch."""
    import numpy as np

    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: _to_host(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
            if f.name != "solve_time" and getattr(obj, f.name) is not None
        }
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_host(v) for v in obj]
    if isinstance(obj, (type(None), bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, (np.generic, np.ndarray)):
        return np.asarray(obj)
    # jax arrays (and anything array-like) convert through numpy.
    return np.asarray(obj)


def payload_fingerprint(payload) -> str:
    """sha256 hex of the canonical textual form of a host payload — the
    bitwise-tier identity (rides `utils.checkpoint.canonicalize`, so the
    same stability contract: dtype + raw bytes, sorted keys)."""
    from sbr_tpu.utils.checkpoint import canonicalize

    return hashlib.sha256(
        canonicalize(_to_host(payload)).encode("utf-8")
    ).hexdigest()


def ulp_diff(a: float, b: float) -> float:
    """Distance in float64 ulps between two scalars (inf when exactly one
    is NaN; 0 when both are — a legitimately-NaN ξ on a no-run surface
    must compare equal to its golden)."""
    import math

    import numpy as np

    a64, b64 = float(a), float(b)
    a_nan, b_nan = math.isnan(a64), math.isnan(b64)
    if a_nan and b_nan:
        return 0.0
    if a_nan or b_nan:
        return math.inf
    ia = np.frombuffer(np.float64(a64).tobytes(), dtype=np.int64)[0]
    ib = np.frombuffer(np.float64(b64).tobytes(), dtype=np.int64)[0]
    # Map the sign-magnitude float ordering onto a monotone integer line.
    ia = int(ia) if ia >= 0 else -(int(ia) & 0x7FFFFFFFFFFFFFFF)
    ib = int(ib) if ib >= 0 else -(int(ib) & 0x7FFFFFFFFFFFFFFF)
    return float(abs(ia - ib))


# ---------------------------------------------------------------------------
# Built-in probes (lazy stack imports; each returns fingerprint + values)
# ---------------------------------------------------------------------------


def _probe_result(payload, values: dict, ok: Optional[bool] = None, **meta) -> dict:
    import numpy as np

    out = {
        "fingerprint": payload_fingerprint(payload),
        "values": {k: float(np.float64(v)) for k, v in values.items()},
        "meta": meta,
    }
    if ok is not None:
        out["ok"] = bool(ok)
    return out


def _probe_grid_baseline() -> dict:
    import numpy as np

    from sbr_tpu.baseline.solver import solve_equilibrium_baseline
    from sbr_tpu.baseline.learning import solve_learning
    from sbr_tpu.models.params import SolverConfig, make_model_params

    cfg = SolverConfig(n_grid=256, bisect_iters=60)
    m = make_model_params()
    ls = solve_learning(m.learning, cfg)
    res = solve_equilibrium_baseline(ls, m.economic, cfg)
    return _probe_result(
        res,
        {"xi": np.asarray(res.xi), "aw_max": np.asarray(res.aw_max),
         "status": np.asarray(res.status)},
        stack="baseline", n_grid=cfg.n_grid,
    )


def _probe_grid_hetero() -> dict:
    import numpy as np

    from sbr_tpu.hetero import solve_equilibrium_hetero, solve_learning_hetero
    from sbr_tpu.models.params import SolverConfig, make_hetero_params

    cfg = SolverConfig(n_grid=256, bisect_iters=60)
    m = make_hetero_params(
        betas=[0.125, 12.5], dist=[0.9, 0.1], eta_bar=30.0, u=0.1, p=0.9,
        kappa=0.3, lam=0.1,
    )
    lsh = solve_learning_hetero(m.learning, cfg)
    res = solve_equilibrium_hetero(lsh, m.economic, cfg)
    return _probe_result(
        res,
        {"xi": np.asarray(res.xi), "status": np.asarray(res.status)},
        stack="hetero", n_grid=cfg.n_grid,
    )


def _probe_grid_interest() -> dict:
    import numpy as np

    from sbr_tpu.baseline.learning import solve_learning
    from sbr_tpu.interest import solve_equilibrium_interest
    from sbr_tpu.models.params import SolverConfig, make_interest_params

    cfg = SolverConfig(n_grid=256, bisect_iters=60)
    m = make_interest_params(u=0.0, r=0.06, delta=0.1)
    ls = solve_learning(m.learning, cfg)
    res = solve_equilibrium_interest(ls, m.economic, cfg)
    return _probe_result(
        res,
        {"xi": np.asarray(res.base.xi), "status": np.asarray(res.base.status)},
        stack="interest", n_grid=cfg.n_grid,
    )


def _probe_grid_social() -> dict:
    import numpy as np

    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.social.solver import solve_equilibrium_social

    m = make_model_params(beta=0.9, eta_bar=30.0, u=0.5, p=0.99, kappa=0.25,
                          lam=0.25)
    res = solve_equilibrium_social(m, SolverConfig(n_grid=512), tol=1e-4,
                                   max_iter=400)
    return _probe_result(
        res,
        {"xi": np.asarray(res.xi), "error": np.asarray(res.error),
         "iterations": np.asarray(res.iterations),
         "converged": np.asarray(res.converged)},
        stack="social",
    )


def _probe_scenario_composed() -> dict:
    import numpy as np

    from sbr_tpu import scenario
    from sbr_tpu.models.params import SolverConfig, make_model_params
    from sbr_tpu.scenario.spec import ScenarioSpec, spec_fingerprint

    spec = ScenarioSpec(modifiers=("insurance_cap", "lolr"))
    base = make_model_params(insurance_cap=0.25, lolr_rate=0.3)
    cfg = SolverConfig(n_grid=256, bisect_iters=50, refine_crossings=False)
    betas = np.linspace(0.4, 1.6, 6)
    us = np.linspace(0.1, 0.9, 6)
    grid = scenario.scenario_grid(spec, betas, us, base, config=cfg)
    payload = {
        "xi": np.asarray(grid.xi),
        "max_aw": np.asarray(grid.max_aw),
        "status": np.asarray(grid.status),
    }
    return _probe_result(
        payload,
        {"run_cells": float(np.sum(np.asarray(grid.status) == 0))},
        scenario=spec_fingerprint(spec, None, cfg, None)[:12],
    )


def _probe_infomodel_gossip() -> dict:
    import numpy as np

    from sbr_tpu.infomodels import InfoModelSpec, simulate_info
    from sbr_tpu.social.agents import AgentSimConfig
    from sbr_tpu.social.graphgen import ErdosRenyiSpec

    spec = InfoModelSpec()  # static gossip — the legacy-reduction surface
    graph = ErdosRenyiSpec(n=400, avg_degree=6.0)
    cfg = AgentSimConfig(n_steps=20, dt=0.1)
    r = simulate_info(spec, graph, beta=1.2, x0=0.02, config=cfg, seed=7)
    payload = {
        f: np.asarray(getattr(r, f))
        for f in ("informed", "t_inf", "informed_frac", "withdrawn_frac")
    }
    return _probe_result(
        payload,
        {"informed_frac_end": payload["informed_frac"][-1]},
        n=graph.n, channel=spec.channel,
    )


def _probe_graphgen_layout() -> dict:
    import numpy as np

    from sbr_tpu.social.graphgen import ErdosRenyiSpec, generate_edges

    # The canonical dst-sorted (src, dst) stream IS the layout the device
    # build is tested bitwise against (`graphgen._selfcheck`) — its bytes
    # are the layout hash.
    spec = ErdosRenyiSpec(n=300, avg_degree=6.0)
    src, dst = generate_edges(spec, seed=3)
    payload = [np.asarray(src), np.asarray(dst)]
    return _probe_result(
        payload,
        {"n_edges": float(len(src))},
        n=spec.n, seed=3,
    )


def _probe_grad_ift_fd() -> dict:
    from sbr_tpu.grad.parity import run_battery as grad_battery
    from sbr_tpu.models.params import SolverConfig

    rep = grad_battery(
        n=4, seed=0, tol=1e-5,
        config=SolverConfig(n_grid=384, bisect_iters=80, refine_crossings=True),
    )
    values = {"worst_rel": rep["worst_rel"], "n_checked": float(rep["n_checked"])}
    return _probe_result(values, values, ok=rep["ok"], tol=rep["tol"])


def _ensure_builtin_probes() -> None:
    global _BUILTINS_REGISTERED
    if _BUILTINS_REGISTERED:
        return
    _BUILTINS_REGISTERED = True
    register_probe("grid.baseline", "bitwise", _probe_grid_baseline,
                   doc="default-params baseline equilibrium")
    register_probe("grid.hetero", "bitwise", _probe_grid_hetero,
                   doc="two-group hetero equilibrium")
    register_probe("grid.interest", "bitwise", _probe_grid_interest,
                   doc="interest-rate equilibrium (r=0.06, delta=0.1)")
    register_probe("grid.social", "ulp", _probe_grid_social, max_ulps=4,
                   doc="social fixed point (Figure-12 params)")
    register_probe("scenario.composed", "bitwise", _probe_scenario_composed,
                   doc="6x6 composed grid (insurance_cap + lolr)")
    register_probe("infomodel.gossip", "bitwise", _probe_infomodel_gossip,
                   doc="static gossip trajectory on seeded ER graph")
    register_probe("graphgen.layout", "bitwise", _probe_graphgen_layout,
                   doc="canonical dst-sorted device layout hash")
    register_probe("grad.ift_fd", "tolerance", _probe_grad_ift_fd, tol=1e-5,
                   requires_x64=True,
                   doc="IFT vs central-FD worst relative error (f64)")


# ---------------------------------------------------------------------------
# Golden registry
# ---------------------------------------------------------------------------


def env_key() -> dict:
    """The registry's content-address: everything a golden is conditioned
    on. Same key ⇒ probes must reproduce the goldens at their tier."""
    import jax

    from sbr_tpu.scenario.spec import SCENARIO_PROGRAM_VERSION
    from sbr_tpu.sweeps.baseline_sweeps import GRID_PROGRAM_VERSION

    return {
        "platform": jax.default_backend(),
        "x64": bool(jax.config.jax_enable_x64),
        "jax": jax.__version__,
        "grid_program": GRID_PROGRAM_VERSION,
        "scenario_program": SCENARIO_PROGRAM_VERSION,
    }


def key_hash(key: dict) -> str:
    from sbr_tpu.utils.checkpoint import canonicalize

    return hashlib.sha256(canonicalize(key).encode("utf-8")).hexdigest()[:16]


def golden_path(reg_dir: Optional[Path] = None, key: Optional[dict] = None) -> Path:
    reg_dir = Path(reg_dir) if reg_dir is not None else registry_dir()
    key = key if key is not None else env_key()
    return reg_dir / f"{_GOLDEN_PREFIX}{key_hash(key)}.json"


def load_goldens(reg_dir: Optional[Path] = None, key: Optional[dict] = None) -> Optional[dict]:
    """Read the golden file for this environment key, or None when absent.

    Raises :class:`AuditRegistryVersionError` (with the regeneration
    hint) when the file was written under a different
    ``AUDIT_REGISTRY_VERSION`` — never silently passes a stale golden."""
    path = golden_path(reg_dir, key)
    if not path.is_file():
        return None
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    version = doc.get("registry_version")
    if version != AUDIT_REGISTRY_VERSION:
        raise AuditRegistryVersionError(
            f"golden file {path} was written at AUDIT_REGISTRY_VERSION "
            f"{version!r} but this build expects {AUDIT_REGISTRY_VERSION}; "
            "regenerate it with `python -m sbr_tpu.obs.audit "
            "--update-goldens` (the old file is archived, not overwritten)"
        )
    return doc


def write_goldens(report: dict, reg_dir: Optional[Path] = None) -> Path:
    """Persist a battery report as the golden set for its key. An existing
    golden is archived (``goldens_<key>.NNN.json``) first — history the
    ``report gc --audit-keep`` retention prunes."""
    reg_dir = Path(reg_dir) if reg_dir is not None else registry_dir()
    reg_dir.mkdir(parents=True, exist_ok=True)
    path = reg_dir / f"{_GOLDEN_PREFIX}{report['key_hash']}.json"
    if path.is_file():
        n = 0
        while (archive := path.with_suffix(f".{n:03d}.json")).exists():
            n += 1
        os.replace(path, archive)
    doc = {
        "registry_version": AUDIT_REGISTRY_VERSION,
        "key": report["key"],
        "key_hash": report["key_hash"],
        "written_at": time.time(),
        "probes": {
            name: {
                "tier": p["tier"],
                "fingerprint": p["fingerprint"],
                "values": p["values"],
            }
            for name, p in report["probes"].items()
            if p["verdict"] not in ("skipped", "error")
        },
    }
    tmp = path.with_suffix(".tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# Classification + the canary runner
# ---------------------------------------------------------------------------


def classify(probe: Probe, result: dict, golden: Optional[dict]) -> tuple:
    """Classify one probe result against its golden at the probe's
    contract tier. Returns ``(verdict, detail)`` — verdict "pass",
    "drift", or "no_golden"."""
    import math

    if golden is None:
        return "no_golden", "no golden recorded for this probe/key"
    if probe.tier == "bitwise":
        if result["fingerprint"] == golden["fingerprint"]:
            return "pass", "fingerprint match"
        return "drift", (
            f"fingerprint {result['fingerprint'][:12]} != golden "
            f"{golden['fingerprint'][:12]}"
        )
    if probe.tier == "ulp":
        gv = golden.get("values") or {}
        if set(result["values"]) != set(gv):
            return "drift", "value key set changed vs golden"
        worst = max((ulp_diff(result["values"][k], gv[k]) for k in gv),
                    default=0.0)
        if worst <= probe.max_ulps:
            return "pass", f"worst {worst:g} ulp (max {probe.max_ulps})"
        return "drift", f"worst {worst:g} ulp over max {probe.max_ulps}"
    # tolerance tier: internal self-check + relative match on each value.
    if result.get("ok") is False:
        return "drift", "probe internal self-check failed"
    gv = golden.get("values") or {}
    if set(result["values"]) != set(gv):
        return "drift", "value key set changed vs golden"
    worst = 0.0
    for k in gv:
        a, b = float(result["values"][k]), float(gv[k])
        if math.isnan(a) or math.isnan(b):
            return "drift", f"non-finite value {k}"
        worst = max(worst, abs(a - b) / max(1.0, abs(b)))
    if worst <= probe.tol:
        return "pass", f"worst rel {worst:.3e} (tol {probe.tol:g})"
    return "drift", f"worst rel {worst:.3e} over tol {probe.tol:g}"


def _apply_canary_fault(result: dict, kind: str) -> None:
    """Apply an ``audit.canary`` injection to a probe RESULT, in place,
    pre-comparison. ``nan`` poisons the values; ``corrupt`` perturbs the
    fingerprint (and values) deterministically — both must be caught by
    the classifier, never reach the serving path."""
    if kind == "nan":
        result["values"] = {k: float("nan") for k in result["values"]}
        result["fingerprint"] = hashlib.sha256(
            ("nan:" + result["fingerprint"]).encode()
        ).hexdigest()
        result["ok"] = False
    elif kind == "corrupt":
        result["values"] = {
            k: v * (1.0 + 1e-3) + 1e-6 for k, v in result["values"].items()
        }
        result["fingerprint"] = hashlib.sha256(
            ("corrupt:" + result["fingerprint"]).encode()
        ).hexdigest()
    result.setdefault("meta", {})["injected_fault"] = kind


def _x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


def run_battery(
    probe_names=None,
    reg_dir: Optional[Path] = None,
    update: bool = False,
    key: Optional[dict] = None,
    cycle: Optional[int] = None,
    emit: bool = True,
) -> dict:
    """Execute the canary battery and classify it against the registry.

    ``probe_names`` restricts the battery (default: ``SBR_AUDIT_PROBES``
    or everything registered); entries may be names or `Probe` objects
    (the test hook). ``update=True`` records the results as the new
    goldens instead of classifying. ``key=None`` derives the environment
    key (imports jax); tests pass an explicit key to stay jax-free.
    ``emit`` controls obs ``audit`` events + the per-cycle artifact.
    """
    from sbr_tpu.resilience import faults

    battery = []
    if probe_names is None:
        probe_names = probe_filter()
    if probe_names is None:
        battery = list(probes().values())
    else:
        reg = probes()
        for entry in probe_names:
            if isinstance(entry, Probe):
                battery.append(entry)
            elif entry in reg:
                battery.append(reg[entry])
            else:
                raise KeyError(
                    f"unknown audit probe {entry!r}; registered: {sorted(reg)}"
                )

    key = key if key is not None else env_key()
    kh = key_hash(key)
    goldens = None
    golden_file = golden_path(reg_dir, key)
    if not update:
        goldens = load_goldens(reg_dir, key)  # may raise the version error

    x64 = None
    t_battery = time.perf_counter()
    report_probes: "OrderedDict[str, dict]" = OrderedDict()
    drift, missing = [], []
    for probe in battery:
        entry = {"tier": probe.tier, "doc": probe.doc}
        if probe.requires_x64:
            if x64 is None:
                x64 = _x64_enabled()
            if not x64:
                entry.update(verdict="skipped",
                             detail="requires x64 (jax_enable_x64 is off)",
                             duration_ms=0.0)
                report_probes[probe.name] = entry
                _emit_probe_event(emit, probe, entry, cycle)
                continue
        t0 = time.perf_counter()
        try:
            result = probe.fn()
        except Exception as err:
            entry.update(verdict="error", detail=repr(err),
                         duration_ms=round((time.perf_counter() - t0) * 1e3, 3))
            drift.append(probe.name)
            report_probes[probe.name] = entry
            _emit_probe_event(emit, probe, entry, cycle)
            continue
        # The chaos-testable injection point: a planted nan/corrupt rule
        # perturbs THIS canary result before comparison (the serving path
        # never sees it) — detection must flag it as drift.
        rule = faults.fire("audit.canary", probe.name)
        if rule is not None and rule.kind in ("nan", "corrupt"):
            _apply_canary_fault(result, rule.kind)
        duration_ms = round((time.perf_counter() - t0) * 1e3, 3)
        entry.update(
            fingerprint=result["fingerprint"],
            values=result["values"],
            meta=result.get("meta", {}),
            duration_ms=duration_ms,
        )
        if "ok" in result:
            entry["ok"] = bool(result["ok"])
        if update:
            entry["verdict"] = "golden"
            entry["detail"] = "recorded as golden"
        else:
            g = (goldens or {}).get("probes", {}).get(probe.name)
            verdict, detail = classify(probe, entry, g)
            entry["verdict"] = verdict
            entry["detail"] = detail
            if g is not None:
                entry["golden_fingerprint"] = g["fingerprint"]
            if verdict == "drift":
                drift.append(probe.name)
            elif verdict == "no_golden":
                missing.append(probe.name)
        report_probes[probe.name] = entry
        _emit_probe_event(emit, probe, entry, cycle)

    report = {
        "registry_version": AUDIT_REGISTRY_VERSION,
        "key": key,
        "key_hash": kh,
        "golden_path": str(golden_file),
        "cycle": cycle,
        "updated": bool(update),
        "probes": report_probes,
        "drift": drift,
        "missing": missing,
        "ok": not update and not drift and not missing and bool(report_probes),
        "duration_s": round(time.perf_counter() - t_battery, 4),
    }
    if update:
        report["golden_path"] = str(write_goldens(report, reg_dir))
    if emit:
        _emit_cycle(report)
    return report


def _emit_probe_event(emit: bool, probe: Probe, entry: dict, cycle) -> None:
    if not emit:
        return
    try:
        from sbr_tpu import obs

        obs.log_audit(
            "probe", probe=probe.name, tier=probe.tier,
            verdict=entry["verdict"], detail=entry.get("detail"),
            duration_ms=entry.get("duration_ms"),
            **({"cycle": cycle} if cycle is not None else {}),
        )
    except Exception:
        pass  # telemetry must never sink the battery


def _emit_cycle(report: dict) -> None:
    """One roll-up ``cycle`` event + the per-cycle artifact file."""
    try:
        from sbr_tpu import obs
        from sbr_tpu.obs import runlog

        verdict = (
            "golden" if report["updated"]
            else "drift" if report["drift"]
            else "no_golden" if report["missing"]
            else "pass"
        )
        obs.log_audit(
            "cycle",
            cycle=report["cycle"], probes=len(report["probes"]),
            drift=len(report["drift"]), missing=len(report["missing"]),
            verdict=verdict, duration_s=report["duration_s"],
            key_hash=report["key_hash"],
        )
        run = runlog.current_run()
        if run is not None:
            _write_battery_artifact(Path(run.run_dir), report)
    except Exception:
        pass


def _write_battery_artifact(run_dir: Path, report: dict) -> None:
    """Land ``audit/battery_NNNN.json`` in the run dir (atomic tmp +
    replace, like `runlog.live_snapshot`); the aged files are what
    ``report gc --audit-keep`` prunes."""
    adir = run_dir / _ARTIFACT_DIR
    adir.mkdir(parents=True, exist_ok=True)
    n = 0
    while (path := adir / f"battery_{n:04d}.json").exists():
        n += 1
    tmp = adir / f".battery_{n:04d}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(report, fh, sort_keys=True, default=str)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# Legacy parity-CLI delegation
# ---------------------------------------------------------------------------


def run_legacy_cli(probe_name: str, check_fn: Callable[[], object],
                   obs_dir: Optional[str] = None) -> int:
    """Run one legacy parity battery through the audit protocol.

    The four historical CLIs (`grad.parity`, `scenario.parity`,
    `infomodels.parity`, `graphgen_cli --selfcheck`) keep their flags and
    output but route execution here: the check runs inside an obs run
    (when ``obs_dir`` is given), its verdict lands as an ``audit`` probe
    event + manifest roll-up, and the exit code is the audit one (0 pass,
    1 drift). ``check_fn`` signals failure by raising (AssertionError for
    the parity batteries) or by returning a nonzero int (graphgen)."""
    from sbr_tpu import obs
    from sbr_tpu.obs import runlog

    run = None
    if obs_dir:
        run = obs.start_run(label=f"audit-{probe_name}", run_dir=obs_dir)
        print(f"obs run dir: {run.run_dir}")
    t0 = time.perf_counter()
    verdict, detail, rc = "pass", "legacy battery passed", 0
    try:
        out = check_fn()
        if isinstance(out, int) and out != 0:
            verdict, detail, rc = "drift", f"legacy battery exit {out}", 1
    except AssertionError as err:
        verdict, detail, rc = "drift", str(err) or "assertion failed", 1
    finally:
        duration_ms = round((time.perf_counter() - t0) * 1e3, 3)
        try:
            obs.log_audit("probe", probe=probe_name, tier="legacy",
                          verdict=verdict, detail=detail,
                          duration_ms=duration_ms)
        except Exception:
            pass
        if run is not None:
            runlog._finalize_if_active(run)
    if verdict == "drift":
        print(f"audit[{probe_name}]: DRIFT — {detail}", file=sys.stderr)
    return rc


# ---------------------------------------------------------------------------
# Fleet scheduler (serve workers)
# ---------------------------------------------------------------------------


class AuditScheduler:
    """Scheduled background canaries inside a serve worker — off the hot
    path. A cycle only starts while the engine is idle (no inflight
    batch, empty queue); a due cycle defers, tick by tick, until the
    window clears — canaries never ride a batch window. A drift verdict
    LATCHES (a worker that failed a correctness canary stays quarantined
    until an operator recycles it): `/healthz` degrades with an
    ``audit_drift`` reason and the router routes around the worker."""

    def __init__(self, engine=None, reg_dir=None, interval: Optional[float] = None,
                 probe_names=None) -> None:
        from sbr_tpu.obs.metrics import DEFAULT_LATENCY_BOUNDS_MS, LabeledHistograms

        self.engine = engine
        self.reg_dir = Path(reg_dir) if reg_dir is not None else registry_dir()
        self.interval = float(interval) if interval is not None else interval_s()
        self.probe_names = tuple(probe_names) if probe_names else probe_filter()
        self.status = "pending"  # pending | pass | drift
        self.cycles = 0
        self.last_pass_ts: Optional[float] = None
        self.last_run_ts: Optional[float] = None
        self.drift_probes: list = []
        self.last_error: Optional[str] = None
        self.hist = LabeledHistograms(DEFAULT_LATENCY_BOUNDS_MS)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --
    def start(self) -> "AuditScheduler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sbr-audit-canary", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    # -- scheduling --
    def _idle(self) -> bool:
        eng = self.engine
        if eng is None:
            return True
        try:
            return (
                eng.live.inflight == 0
                and eng.live.queue_depth == 0
                and eng._queue.qsize() == 0
            )
        except Exception:
            return True

    def _loop(self) -> None:
        next_at = time.monotonic() + self.interval
        while not self._stop.wait(0.2):
            if time.monotonic() < next_at:
                continue
            if not self._idle():
                continue  # defer the due cycle; re-check next tick
            self.run_cycle()
            next_at = time.monotonic() + self.interval

    def run_cycle(self) -> Optional[dict]:
        """Execute one canary cycle now (also the test hook)."""
        cycle = self.cycles + 1
        try:
            report = run_battery(
                probe_names=self.probe_names, reg_dir=self.reg_dir, cycle=cycle,
            )
        except Exception as err:
            with self._lock:
                self.cycles = cycle
                self.last_run_ts = time.time()
                self.last_error = repr(err)
            try:
                from sbr_tpu import obs

                obs.log_audit("error", cycle=cycle, error=repr(err))
            except Exception:
                pass
            return None
        with self._lock:
            self.cycles = cycle
            self.last_run_ts = time.time()
            self.last_error = None
            for name, p in report["probes"].items():
                if p.get("duration_ms"):
                    self.hist.record(name, p["duration_ms"])
            if report["drift"]:
                self.status = "drift"  # latched
                self.drift_probes = list(report["drift"])
            elif self.status != "drift" and report["ok"]:
                self.status = "pass"
                self.last_pass_ts = time.time()
        return report

    # -- surfacing --
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "status": self.status,
                "cycles": self.cycles,
                "interval_s": self.interval,
                "last_pass_ts": self.last_pass_ts,
                "last_run_ts": self.last_run_ts,
                "drift_probes": list(self.drift_probes),
                "last_error": self.last_error,
                "probe_ms": self.hist.summaries(),
            }

    def heartbeat_block(self) -> dict:
        """The compact block riding worker heartbeats (what the router's
        quarantine check reads)."""
        with self._lock:
            return {
                "status": self.status,
                "cycles": self.cycles,
                "last_pass_ts": self.last_pass_ts,
                "drift_probes": list(self.drift_probes),
            }

    def status_gauge(self) -> int:
        """``sbr_audit_status`` encoding: 1 pass, 0 pending, -1 drift."""
        return {"pass": 1, "drift": -1}.get(self.status, 0)

    def prometheus_lines(self) -> list:
        lines = [
            "# TYPE sbr_audit_status gauge",
            f"sbr_audit_status {self.status_gauge()}",
        ]
        lines.extend(self.hist.to_prometheus("sbr_audit_probe_ms",
                                             label_key="probe"))
        return lines


# ---------------------------------------------------------------------------
# Retention (report gc --audit-keep)
# ---------------------------------------------------------------------------


def gc_audit_files(root, keep: int = 4, reg_dir: Optional[Path] = None,
                   running_grace_s: float = 6 * 3600.0) -> list:
    """Prune aged audit artifacts, mirroring the ``--trace-keep``
    contract: per run dir under ``root``, keep the newest ``keep``
    ``audit/battery_NNNN.json`` files; live runs (manifest "running" with
    recent mtime) are never touched. Also prunes archived golden
    snapshots (``goldens_<key>.NNN.json``) beyond ``keep`` per key in
    ``reg_dir`` (default: the active registry dir, when it exists) —
    active ``goldens_<key>.json`` files are never candidates (the glob
    requires the archive's second dot). Returns the removed paths."""
    from sbr_tpu.obs import runlog

    keep = max(int(keep), 0)
    removed: list = []
    root = Path(root)
    if root.is_dir():
        for d in sorted(p for p in root.iterdir() if p.is_dir()):
            adir = d / _ARTIFACT_DIR
            if not adir.is_dir():
                continue
            if runlog._run_is_live(d, running_grace_s):
                continue
            batteries = sorted(adir.glob("battery_*.json"))
            for path in batteries[: max(len(batteries) - keep, 0)]:
                try:
                    path.unlink()
                    removed.append(str(path))
                except OSError:
                    pass
    reg_dir = Path(reg_dir) if reg_dir is not None else registry_dir()
    if reg_dir.is_dir():
        by_key: dict = {}
        for path in reg_dir.glob(f"{_GOLDEN_PREFIX}*.*.json"):
            stem = path.name.split(".")[0]
            by_key.setdefault(stem, []).append(path)
        for archives in by_key.values():
            archives.sort()
            for path in archives[: max(len(archives) - keep, 0)]:
                try:
                    path.unlink()
                    removed.append(str(path))
                except OSError:
                    pass
    return removed


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.audit",
        description="Unified numerics audit battery: golden-surface "
        "registry + canary probes at documented contract tiers "
        "(bitwise/ulp/tolerance). Exit 0 pass, 1 drift, 2 registry "
        "version error, 3 no goldens for this environment key.",
    )
    parser.add_argument("--update-goldens", action="store_true",
                        help="record this battery's results as the golden "
                        "set for the current environment key")
    parser.add_argument("--registry", default=None,
                        help="golden registry dir (default "
                        "SBR_AUDIT_REGISTRY_DIR or ~/.cache/sbr_tpu/"
                        "audit_goldens)")
    parser.add_argument("--probes", default=None,
                        help="csv probe subset (default SBR_AUDIT_PROBES "
                        "or the full battery)")
    parser.add_argument("--obs-dir", default=None,
                        help="run the battery inside an obs run rooted "
                        "here (dir printed; report audit gates on it)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--list", action="store_true",
                        help="list registered probes and exit")
    args = parser.parse_args(argv)

    if args.list:
        for p in probes().values():
            print(f"{p.name:20s} {p.tier:10s} {p.doc}")
        return 0

    import jax

    # Like the legacy parity CLIs: the full battery's contracts (the grad
    # FD oracle above all) are f64 contracts, so the CLI pins x64. Serve
    # workers never take this path — their scheduler audits the precision
    # they actually serve at (the env key separates the two golden sets).
    jax.config.update("jax_enable_x64", True)

    probe_names = None
    if args.probes:
        probe_names = tuple(p.strip() for p in args.probes.split(",") if p.strip())

    from sbr_tpu import obs
    from sbr_tpu.obs import runlog

    run = None
    if args.obs_dir:
        run = obs.start_run(label="audit", run_dir=args.obs_dir)
        print(f"obs run dir: {run.run_dir}")
    try:
        report = run_battery(
            probe_names=probe_names, reg_dir=args.registry,
            update=args.update_goldens,
        )
    except AuditRegistryVersionError as err:
        print(f"audit: {err}", file=sys.stderr)
        return 2
    finally:
        if run is not None:
            runlog._finalize_if_active(run)

    if args.json:
        print(json.dumps(report, default=str))
    else:
        for name, p in report["probes"].items():
            mark = {"pass": "PASS ", "golden": "GOLD ", "drift": "DRIFT",
                    "no_golden": "MISS ", "skipped": "SKIP ",
                    "error": "ERROR"}.get(p["verdict"], "?    ")
            print(f"{mark} {name:20s} [{p['tier']:9s}] "
                  f"{p.get('duration_ms', 0):9.1f} ms  {p.get('detail', '')}")
        print(
            f"audit battery: {len(report['probes'])} probe(s), "
            f"{len(report['drift'])} drift, {len(report['missing'])} "
            f"missing, key {report['key_hash']} "
            f"-> {report['golden_path']}"
        )
    if args.update_goldens:
        return 0
    if report["drift"]:
        return 1
    if report["missing"] or not report["probes"]:
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
