"""Structured run telemetry: `RunContext` event logs, per-stage spans, and
AOT compile/execute attribution.

The reference package's only observability is ad-hoc prints plus a
`solve_time` field per result struct (SURVEY §5.1, §5.5) — none of which
survives `jit`. This module is the structured replacement:

- A **RunContext** owns a per-run directory holding `events.jsonl` (one
  structured event per line: stage start/end, jit compile/execute splits,
  status-grid accounting, device/memory snapshots) plus a single
  machine-readable `manifest.json` summarizing the run. The manifest is
  written at start (status "running") and atomically rewritten at
  finalize, so an interrupted run still leaves a parseable artifact.
- **Spans** (`obs.span`) time named pipeline stages at the HOST boundary
  with an honest device fence (`obs.timing.fence`). Inside traced code
  they are no-ops (`jax.core.trace_state_clean` guard), so instrumented
  library functions behave identically under `vmap`/`jit`.
- **jit_call** attributes a jitted entry point's wall-clock to trace vs
  compile vs execute via the AOT path (`fn.lower(args).compile()`), plus
  XLA cost/memory analysis of the compiled executable. Compiled
  executables are cached per (fn, abstract signature) inside the run, so
  steady-state calls report a pure execute time with `cache: "hit"`.

Zero-overhead contract when disabled: every module-level helper first
checks for an active run (one global read) and returns immediately —
no jax import, no clock read, no allocation. Nothing here ever inserts
host callbacks or changes traced code, so enabling telemetry cannot
trigger retraces of library jit caches (asserted by tests/test_obs.py).
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import sys
import time
from pathlib import Path
from typing import Optional

from sbr_tpu.obs.metrics import metrics

SCHEMA = "sbr-obs/1"

# Active run stack: module-level so instrumentation sites need one global
# read on the disabled path. The env var SBR_OBS=1 auto-starts a run lazily
# on the first instrumented call (dir from SBR_OBS_DIR, default obs_runs/).
_STACK: list = []
_ENV_CHECKED = False

# Innermost-open-span names: the compile listeners (obs.prof) attribute XLA
# compiles to whatever stage was active when the compile fired.
_SPAN_NAMES: list = []


def active_run():
    """The active RunContext WITHOUT the SBR_OBS auto-start side effect —
    for listeners/hooks that may fire at arbitrary points (obs.prof's
    jax.monitoring callbacks must never start a run mid-compile)."""
    return _STACK[-1] if _STACK else None


def active_span() -> Optional[str]:
    """Name of the innermost open span, or None outside any span."""
    return _SPAN_NAMES[-1] if _SPAN_NAMES else None


def _trace_clean() -> bool:
    """True when not inside a jax trace (host instrumentation is allowed)."""
    try:
        import jax

        return jax.core.trace_state_clean()
    except Exception:  # ancient/newer jax without the helper: fail open
        return True


def _json_default(obj):
    """Best-effort JSON coercion for numpy/jax scalars and arrays."""
    try:
        import numpy as np

        if isinstance(obj, np.generic):
            return obj.item()
    except Exception:
        pass
    # numpy ndarrays and jax Arrays both expose tolist(); scalars item().
    if hasattr(obj, "tolist"):
        try:
            return obj.tolist()
        except Exception:
            pass
    if hasattr(obj, "item"):
        try:
            return obj.item()
        except Exception:
            pass
    return repr(obj)


class _Span:
    """Live span handle: accumulate arrays to fence at exit via `.sync()`."""

    __slots__ = ("_arrays",)

    def __init__(self) -> None:
        self._arrays: list = []

    def sync(self, *arrays) -> None:
        """Register arrays whose producing computation must complete before
        the span's end time is taken (the honest-fence contract)."""
        self._arrays.extend(arrays)


class _NullSpan:
    """Disabled-path span: every method is a no-op."""

    __slots__ = ()

    def sync(self, *arrays) -> None:
        pass


_NULL_SPAN = _NullSpan()


class RunContext:
    """One telemetry run: a directory with `events.jsonl` + `manifest.json`.

    Construction touches only the filesystem — never a JAX backend — so the
    bench harness parent (which must not initialize an accelerator) can hold
    a RunContext safely; device info is captured lazily on the first
    instrumented call that already implies a live backend.
    """

    def __init__(
        self,
        run_dir: Optional[str] = None,
        label: str = "run",
        root: Optional[str] = None,
        auto_prune_keep: Optional[int] = None,
    ) -> None:
        if run_dir is None:
            root = Path(root or os.environ.get("SBR_OBS_DIR", "obs_runs"))
            stamp = time.strftime("%Y%m%dT%H%M%S")
            base = root / f"{label}_{stamp}_p{os.getpid()}"
            # The stamp has second granularity: two same-label runs within
            # one second must not share a directory (interleaved events,
            # clobbered manifest) — claim a unique dir with exist_ok=False.
            run_dir, i = base, 0
            while True:
                try:
                    run_dir.mkdir(parents=True, exist_ok=False)
                    break
                except FileExistsError:
                    i += 1
                    run_dir = Path(f"{base}_{i}")
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.label = label
        self.t_wall0 = time.time()
        self.t_mono0 = time.monotonic()
        self._fh = open(self.run_dir / "events.jsonl", "a")
        self._n_events = 0
        self._closed = False
        # aggregates folded into the manifest
        self.stages: dict = {}  # name -> {count, total_s}
        self.jit: dict = {"calls": 0, "cache_hits": 0, "trace_s": 0.0, "compile_s": 0.0, "execute_s": 0.0}
        self.mem_peak_live = 0  # peak sum of live jax buffer nbytes
        self.mem_peak_device = 0  # peak allocator peak_bytes_in_use (if exposed)
        # Memory observatory (obs.mem): per-span/per-tile attribution,
        # preflight verdicts, and the capacity-planner decision.
        self.mem_peak_span: Optional[str] = None  # span holding the peak snapshot
        self.mem_capacity: Optional[int] = None  # allocator bytes_limit, if exposed
        self.mem_programs: dict = {}  # program -> XLA footprint (arg/out/temp bytes)
        self.mem_tiles: dict = {}  # tile_id -> peak bytes
        self.mem_plan: Optional[dict] = None  # capacity-planner decision record
        self.mem_preflights: list = []  # preflight verdict records
        self._mem_last: dict = {}  # previous snapshot, for per-event deltas
        self.device: Optional[dict] = None
        self.health: dict = {}  # stage -> folded numerical-health roll-up
        # Resilience roll-ups (sbr_tpu.resilience): injected-fault firings,
        # retry-engine attempt outcomes, and self-healing repair actions.
        self.resilience: dict = {"faults": {}, "retries": {}, "repairs": {}}
        # Elastic-scheduler roll-ups (resilience.elastic): scheduler
        # actions (join/claim/reclaim/done/leave/plan), cross-run tile
        # cache outcomes (hit/miss/store/quarantine), and tiles by source.
        self.elastic: dict = {"scheduler": {}, "cache": {}, "tiles": {}}
        # Serving-fleet roll-up (sbr_tpu.serve.fleet/router): per-action
        # counts of fleet events (route failovers, hedges, sheds, degraded
        # ladder answers, breaker transitions, worker joins/losses) — what
        # `report fleet` gates on.
        self.fleet: dict = {}
        # Information-model roll-up (sbr_tpu.infomodels): per-action counts
        # of infomodel events (rewire epochs, belief censuses, fixed-point
        # solves, closure comparisons, population queries) plus the
        # nonconverged/breach tallies `report infomodel` gates on.
        self.infomodel: dict = {}
        # Numerics-audit roll-up (sbr_tpu.obs.audit): per-action counts of
        # audit events plus the drift/pass probe tallies and last cycle —
        # what `report audit` gates on.
        self.audit: dict = {}
        # Workload-demand roll-up (sbr_tpu.obs.demand): per-action counts
        # of demand lifecycle events (snapshot rotations, advisor-plan
        # writes) plus the last plan fingerprint. Deliberately NOT
        # per-query — the demand tracker aggregates in memory and only
        # its artifact writes land here.
        self.demand: dict = {}
        # Prefetch-controller roll-up (sbr_tpu.serve.prewarm): per-action
        # event counts, abandoned-tile counts by reason, and the last plan
        # fingerprint acted on — what `report prewarm` gates.
        self.prewarm: dict = {}
        # Flight-recorder roll-up (sbr_tpu.obs.flight): per-action counts
        # of flight lifecycle events (snapshot rotations, the final write)
        # plus the headline utilization fractions of the final snapshot —
        # what `report util` falls back on for a torn flight.json.
        self.flight: dict = {}
        self._aot_cache: dict = {}
        # Performance observatory (obs.prof): XLA compile attribution from
        # the jax.monitoring listeners, per-run retrace accounting, and
        # profiler-capture summaries. Listener installation is idempotent
        # and jax-import-free until a compile actually fires.
        from sbr_tpu.obs import prof

        prof.install()
        self.xla: dict = {
            "compiles": 0,
            "jaxpr_trace_s": 0.0,
            "mlir_lowering_s": 0.0,
            "backend_compile_s": 0.0,
            "by_span": {},
        }
        self._trace_counts0 = prof.trace_counts()
        self.profiles: list = []
        # Retention: prune sibling run dirs at finalize when a keep budget
        # is configured (SBR_OBS_KEEP env, or explicit ctor argument — the
        # bench harness and the SBR_OBS=1 auto-start path set one).
        if auto_prune_keep is None:
            env_keep = os.environ.get("SBR_OBS_KEEP", "").strip()
            auto_prune_keep = int(env_keep) if env_keep else None
        self._auto_prune_keep = auto_prune_keep
        self._metrics_was_on = metrics().enabled
        if not self._metrics_was_on:
            # This run owns the registry: start it from zero so the manifest
            # carries per-run metrics, not process-lifetime accumulation.
            metrics().reset()
        metrics().enable()
        self._write_manifest(status="running")
        self.event("run_start", label=label, argv=list(sys.argv), pid=os.getpid())

    # -- events -------------------------------------------------------------
    def event(self, kind: str, **fields) -> None:
        """Append one structured event line. `mono` is seconds since run
        start on the monotonic clock (orders events); `ts` is wall time."""
        if self._closed:
            return
        rec = {
            "mono": round(time.monotonic() - self.t_mono0, 9),
            "ts": round(time.time(), 6),
            "kind": kind,
        }
        rec.update(fields)
        self._fh.write(json.dumps(rec, default=_json_default) + "\n")
        self._fh.flush()
        self._n_events += 1

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Stage span: emits stage_start/stage_end events and accumulates
        per-stage totals. Yields a handle whose `.sync(*arrays)` registers
        arrays to fence before the end timestamp (device-honest timing)."""
        from sbr_tpu.obs import prof

        self.event("stage_start", stage=name, **attrs)
        handle = _Span()
        _SPAN_NAMES.append(name)
        t0 = time.monotonic()
        err = None
        try:
            # With SBR_OBS_PROFILE=1 the stage also lands as a
            # TraceAnnotation on the xplane timeline; otherwise free.
            with prof.annotate(name):
                yield handle
        except BaseException as e:
            err = e
            raise
        finally:
            if _SPAN_NAMES and _SPAN_NAMES[-1] == name:
                _SPAN_NAMES.pop()
            if handle._arrays:
                try:
                    from sbr_tpu.obs.timing import fence

                    fence(*handle._arrays)
                except Exception:
                    pass  # fencing must never sink the instrumented call
            dur = time.monotonic() - t0
            agg = self.stages.setdefault(name, {"count": 0, "total_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += dur
            end_fields = dict(stage=name, dur_s=round(dur, 6), **attrs)
            if err is not None:
                end_fields["error"] = repr(err)
            self.event("stage_end", **end_fields)
            self._memory_event(name)

    # -- jit compile/execute attribution ------------------------------------
    def jit_call(self, name: str, fn, *args):
        """Call jitted ``fn(*args)`` through the AOT path, attributing
        wall-clock to trace/lower vs compile vs execute and logging XLA
        cost/memory analysis. Falls back to a plain call (with a fallback
        event) if the function cannot be lowered."""
        sig = _abstract_sig(args)
        key = (name, id(fn), sig)
        entry = self._aot_cache.get(key)
        trace_s = compile_s = 0.0
        info: dict = {}
        if entry is None:
            t0 = time.monotonic()
            try:
                lowered = fn.lower(*args)
                t1 = time.monotonic()
                compiled = lowered.compile()
                t2 = time.monotonic()
            except Exception as err:
                self.event("jit_call_fallback", name=name, error=repr(err))
                return fn(*args)
            trace_s = t1 - t0
            compile_s = t2 - t1
            info = _compiled_info(compiled)
            self._note_program_mem(name, info)
            entry = (compiled, info)
            self._aot_cache[key] = entry
            cache = "miss"
        else:
            compiled, info = entry
            cache = "hit"
        compiled = entry[0]
        t3 = time.monotonic()
        out = compiled(*args)
        try:
            from sbr_tpu.obs.timing import fence

            import jax

            fence(*jax.tree_util.tree_leaves(out))
        except Exception:
            pass
        execute_s = time.monotonic() - t3
        self.jit["calls"] += 1
        self.jit["cache_hits"] += int(cache == "hit")
        self.jit["trace_s"] += trace_s
        self.jit["compile_s"] += compile_s
        self.jit["execute_s"] += execute_s
        self.event(
            "jit_call",
            name=name,
            cache=cache,
            trace_s=round(trace_s, 6),
            compile_s=round(compile_s, 6),
            execute_s=round(execute_s, 6),
            **info,
        )
        self._device_event()
        self._memory_event(name)
        return out

    # -- device / memory snapshots ------------------------------------------
    def _device_event(self) -> None:
        """Record device info once, from a context where a backend is
        already live (never force backend init from telemetry)."""
        if self.device is not None:
            return
        try:
            import jax

            d = jax.devices()[0]
            self.device = {
                "platform": d.platform,
                "device_kind": d.device_kind,
                "device_count": jax.device_count(),
                "process_count": getattr(jax, "process_count", lambda: 1)(),
                "jax_version": jax.__version__,
            }
            self.event("device", **self.device)
        except Exception:
            pass

    def _memory_event(self, where: str) -> None:
        """Attribution snapshot (obs.mem): live-buffer sum (gated by
        SBR_OBS_MEM_LIVE — O(live arrays) per event) plus allocator stats
        when exposed (`memory_stats` is None on CPU and may be unsupported
        behind tunnels), emitted as a ``mem`` event with deltas vs the
        previous snapshot and folded into the peak/peak-span roll-up."""
        try:
            # Only span ends and jit calls land here, both of which imply
            # device work already happened — so recording the device info
            # cannot be the thing that forces backend init.
            self._device_event()
            from sbr_tpu.obs import mem

            snap = mem.snapshot()
            if not snap:
                return
            ev = {"where": where, "span": active_span(), **snap}
            for k in ("live_buffer_bytes", "bytes_in_use"):
                if k in snap and k in self._mem_last:
                    ev["d_" + k] = snap[k] - self._mem_last[k]
            self._mem_last.update(snap)
            if "bytes_limit" in snap:
                self.mem_capacity = snap["bytes_limit"]
            live = snap.get("live_buffer_bytes")
            device_now = max(snap.get("peak_bytes_in_use", 0), snap.get("bytes_in_use", 0))
            if live is not None and live > self.mem_peak_live:
                self.mem_peak_live = live
                if not device_now:  # live sum is the only signal (CPU)
                    self.mem_peak_span = where
            if device_now > self.mem_peak_device:
                self.mem_peak_device = device_now
                self.mem_peak_span = where
            self.event("mem", **ev)
        except Exception:
            pass

    def _note_program_mem(self, name: str, info: dict) -> None:
        """Fold one compiled program's XLA footprint (jit_call's
        memory_analysis) into the per-program registry — the manifest's
        top-programs-by-temp-size table reads from here."""
        keys = ("arg_bytes", "out_bytes", "temp_bytes", "code_bytes")
        fp = {k: int(info[k]) for k in keys if k in info}
        if not fp:
            return
        prev = self.mem_programs.get(name)
        if prev is None or fp.get("temp_bytes", 0) >= prev.get("temp_bytes", 0):
            self.mem_programs[name] = fp

    def log_tile_mem(self, tile: str, **snap) -> None:
        """Per-tile peak attribution (the tiled sweep loop calls this after
        each computed tile): one ``mem`` event with a ``tile`` field, folded
        into the manifest's per-tile peak table. The tile's figure is
        ``bytes_in_use`` at snapshot time (taken while the tile's buffers
        are live) — NOT ``peak_bytes_in_use``, which is a process-lifetime
        high-water mark: after one big tile (or a compile spike) it would
        attribute the global peak to every later tile and `report memory`
        would flag them all. The monotone counter is still recorded in the
        event and handled at run level (``peak_span``)."""
        from sbr_tpu.obs import mem

        self.event("mem", where="tile", tile=tile, span=active_span(), **snap)
        self.mem_tiles[tile] = max(mem.tile_peak(snap), self.mem_tiles.get(tile, 0))
        if "bytes_limit" in snap:
            self.mem_capacity = int(snap["bytes_limit"])

    def log_preflight(self, rec: dict) -> None:
        """OOM-preflight verdict (obs.mem.preflight): one ``preflight``
        event + an entry in the manifest's ``memory.preflight`` list."""
        self.event("preflight", **rec)
        self.mem_preflights.append(dict(rec))

    def log_plan(self, rec: dict) -> None:
        """Capacity-planner decision (tile_shape="auto"): one ``plan``
        event; the manifest's ``memory.plan`` block records the last one."""
        self.event("plan", **rec)
        self.mem_plan = dict(rec)

    # -- performance observatory hooks (obs.prof) -----------------------------
    def _note_xla(self, key: str, duration_s: float, span: Optional[str]) -> None:
        """Fold one XLA compile-phase duration (from the jax.monitoring
        listeners) into the run, attributed to the innermost open span.
        Called from a listener mid-compile: must stay cheap and non-raising."""
        self.xla[key] = self.xla.get(key, 0.0) + duration_s
        if key == "backend_compile_s":
            self.xla["compiles"] += 1
            agg = self.xla["by_span"].setdefault(
                span or "-", {"compiles": 0, "backend_compile_s": 0.0}
            )
            agg["compiles"] += 1
            agg["backend_compile_s"] += duration_s
        self.event(
            "xla_compile", phase=key[: -len("_s")], duration_s=round(duration_s, 6), span=span
        )

    def _note_trace(self, name: str, total: int) -> None:
        """Per-run retrace accounting (obs.prof.note_trace): when the
        within-run trace count for ``name`` exceeds its budget, emit a
        ``retrace`` warning event — the signature of argument shape/dtype
        churn recompiling a hot program. Fires DURING tracing, which is
        fine: the event is pure host-side file IO."""
        from sbr_tpu.obs import prof

        count = total - self._trace_counts0.get(name, 0)
        budget = prof.trace_budget(name)
        if count > budget:
            self.event(
                "retrace",
                name=name,
                count=count,
                total=total,
                budget=budget,
                span=active_span(),
                hint="trace count exceeds budget — argument shape/dtype churn?",
            )

    def _retrace_summary(self) -> dict:
        """Per-name trace counts accumulated DURING this run (manifest
        roll-up; over_budget mirrors the retrace warning events)."""
        from sbr_tpu.obs import prof

        out = {}
        for name, total in sorted(prof.trace_counts().items()):
            count = total - self._trace_counts0.get(name, 0)
            if count > 0:
                budget = prof.trace_budget(name)
                out[name] = {
                    "traces": count,
                    "budget": budget,
                    "over_budget": count > budget,
                }
        return out

    # -- summary / finalize ---------------------------------------------------
    def summary(self) -> dict:
        """Machine-readable roll-up (the bench JSON `obs` block)."""
        retraces = self._retrace_summary()
        return {
            "run_dir": str(self.run_dir),
            "device": (self.device or {}).get("device_kind"),
            "platform": (self.device or {}).get("platform"),
            "compile_s": round(self.jit["compile_s"], 4),
            "execute_s": round(self.jit["execute_s"], 4),
            "jit_calls": self.jit["calls"],
            "memory_peak_bytes": self.mem_peak_device or self.mem_peak_live,
            "n_events": self._n_events,
            "xla_compiles": self.xla["compiles"],
            "xla_backend_compile_s": round(self.xla["backend_compile_s"], 4),
            "retraces_over_budget": sum(1 for v in retraces.values() if v["over_budget"]),
        }

    def _xla_manifest(self) -> dict:
        """The jax.monitoring compile-attribution block (durations rounded;
        `monitoring: false` flags a jax build without the listener API, so a
        zeroed block reads as "couldn't watch", not "nothing compiled")."""
        from sbr_tpu.obs import prof

        return {
            "monitoring": prof.monitoring_available(),
            "compiles": self.xla["compiles"],
            **{
                k: round(self.xla[k], 6)
                for k in ("jaxpr_trace_s", "mlir_lowering_s", "backend_compile_s")
            },
            "by_span": {
                k: {"compiles": v["compiles"], "backend_compile_s": round(v["backend_compile_s"], 6)}
                for k, v in sorted(self.xla["by_span"].items())
            },
        }

    _MANIFEST_TILE_CAP = 512  # largest per-tile table the manifest carries

    def _memory_manifest(self) -> dict:
        """The manifest ``memory`` roll-up: peaks (+ the span holding the
        peak), device capacity, the top-5 programs by XLA temp size, the
        per-tile peak table (size-capped — the event log keeps every tile),
        and the planner/preflight records."""
        from sbr_tpu.obs import mem

        top = sorted(
            self.mem_programs.items(), key=lambda kv: -kv[1].get("temp_bytes", 0)
        )[:5]
        tiles = self.mem_tiles
        truncated = 0
        if len(tiles) > self._MANIFEST_TILE_CAP:
            truncated = len(tiles) - self._MANIFEST_TILE_CAP
            tiles = dict(
                sorted(tiles.items(), key=lambda kv: -kv[1])[: self._MANIFEST_TILE_CAP]
            )
        block = {
            "peak_live_buffer_bytes": self.mem_peak_live,
            "peak_device_bytes": self.mem_peak_device,
            "peak_bytes": self.mem_peak_device or self.mem_peak_live,
            "peak_span": self.mem_peak_span,
            "capacity_bytes": self.mem_capacity,
            "headroom": mem.headroom(),
            "top_programs": [{"name": k, **v} for k, v in top] or None,
            "tiles": tiles or None,
            "plan": self.mem_plan,
            "preflight": self.mem_preflights or None,
        }
        if truncated:
            block["tiles_truncated"] = truncated
        return block

    def _write_manifest(self, status: str) -> None:
        manifest = {
            "schema": SCHEMA,
            "label": self.label,
            "status": status,
            "started_at": time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(self.t_wall0)),
            "duration_s": round(time.monotonic() - self.t_mono0, 6),
            "argv": list(sys.argv),
            "n_events": self._n_events,
            "device": self.device,
            "stages": {
                k: {"count": v["count"], "total_s": round(v["total_s"], 6)}
                for k, v in sorted(self.stages.items())
            },
            "jit": {
                **{k: self.jit[k] for k in ("calls", "cache_hits")},
                **{k: round(self.jit[k], 6) for k in ("trace_s", "compile_s", "execute_s")},
            },
            "memory": self._memory_manifest(),
            "health": self.health or None,
            "resilience": self._resilience_manifest(),
            "elastic": self._elastic_manifest(),
            "fleet": self.fleet or None,
            "infomodel": self.infomodel or None,
            "audit": self.audit or None,
            "demand": self.demand or None,
            "prewarm": self.prewarm or None,
            "flight": self.flight or None,
            "metrics": metrics().summary() if metrics().enabled else None,
            "xla": self._xla_manifest(),
            "retraces": self._retrace_summary() or None,
            "profiles": self.profiles or None,
            "trace": self._trace_manifest(),
        }
        tmp = self.run_dir / "manifest.json.tmp"
        tmp.write_text(json.dumps(manifest, indent=1, default=_json_default) + "\n")
        os.replace(tmp, self.run_dir / "manifest.json")

    def _trace_manifest(self) -> Optional[dict]:
        """Distributed-tracing counter roll-up (ISSUE 16): the run's
        `TraceWriter` counters, when any process committed spans here."""
        try:
            from sbr_tpu.obs import trace as _trace

            return _trace.summary_for(self.run_dir)
        except Exception:
            return None

    def live_snapshot(self, doc: dict, name: str = "live.json") -> Path:
        """Atomically rewrite a rolling snapshot file inside the run dir
        (tmp + ``os.replace``, the manifest discipline) — how a LONG-LIVED
        process (the serving engine's ``live.json``) exposes queryable
        state to `report` while still running. Readers always see a
        complete document; writers may call this at any cadence."""
        tmp = self.run_dir / (name + ".tmp")
        tmp.write_text(json.dumps(doc, default=_json_default) + "\n")
        dest = self.run_dir / name
        os.replace(tmp, dest)
        return dest

    def log_health(self, stage: str, summary: dict) -> None:
        """Emit one ``health`` event and fold it into the per-stage manifest
        roll-up (sum cells/divergent, max residual, summed flag counts)."""
        self.event("health", stage=stage, **summary)
        agg = self.health.setdefault(
            stage, {"cells": 0, "divergent": 0, "max_residual": None, "flag_counts": {}}
        )
        agg["cells"] += int(summary.get("cells", 0))
        agg["divergent"] += int(summary.get("divergent", 0))
        mr = summary.get("max_residual")
        if mr is not None:
            prev = agg["max_residual"]
            agg["max_residual"] = mr if prev is None else max(prev, mr)
        for name, n in (summary.get("flag_counts") or {}).items():
            agg["flag_counts"][name] = agg["flag_counts"].get(name, 0) + int(n)

    def log_fault(self, point: str, kind: str = "?", **fields) -> None:
        """Emit one injected-``fault`` event (`resilience.faults`) and count
        it per (point, kind) in the manifest roll-up."""
        self.event("fault", point=point, fault=kind, **fields)
        key = f"{point}:{kind}"
        agg = self.resilience["faults"]
        agg[key] = agg.get(key, 0) + 1

    def log_retry(self, scope: str, outcome: str, attempt: int = 0, **fields) -> None:
        """Emit one ``retry`` attempt-outcome event (`resilience.retry`) and
        fold it into the per-scope manifest roll-up. ``gave_up`` /
        ``budget_exhausted`` scopes are what `report resilience` gates on."""
        self.event("retry", scope=scope, outcome=outcome, attempt=attempt, **fields)
        agg = self.resilience["retries"].setdefault(
            scope, {"attempts": 0, "recovered": 0, "gave_up": 0}
        )
        agg["attempts"] = max(agg["attempts"], int(attempt))
        if outcome == "recovered":
            agg["recovered"] += 1
        elif outcome in ("gave_up", "budget_exhausted"):
            agg["gave_up"] += 1

    def log_repair(self, action: str, target: str = "?", ok: bool = True, **fields) -> None:
        """Emit one self-healing ``repair`` event (`resilience.heal`, the
        multihost work-stealing adoption) and count it per action."""
        self.event("repair", action=action, target=target, ok=bool(ok), **fields)
        agg = self.resilience["repairs"].setdefault(action, {"count": 0, "failed": 0})
        agg["count"] += 1
        agg["failed"] += int(not ok)

    def log_scheduler(self, action: str = "?", **fields) -> None:
        """Emit one elastic-scheduler ``scheduler`` event
        (`resilience.elastic`) and count it per action in the manifest
        roll-up; ``done`` events also count their tile ``source``
        (computed / cache / local) — what `report elastic` gates on."""
        self.event("scheduler", action=action, **fields)
        agg = self.elastic["scheduler"]
        agg[action] = agg.get(action, 0) + 1
        if action == "done":
            source = str(fields.get("source", "?"))
            tiles = self.elastic["tiles"]
            tiles[source] = tiles.get(source, 0) + 1

    def log_cache(self, action: str = "?", **fields) -> None:
        """Emit one cross-run tile-cache ``cache`` event
        (`resilience.elastic.TileCache`) and count it per action."""
        self.event("cache", action=action, **fields)
        agg = self.elastic["cache"]
        agg[action] = agg.get(action, 0) + 1

    def log_fleet(self, action: str = "?", **fields) -> None:
        """Emit one serving-fleet ``fleet`` event (router forwards,
        failovers, hedges, sheds, breaker transitions, degraded-ladder
        answers — `sbr_tpu.serve`) and count it per action in the manifest
        roll-up (`report fleet` gates on these counts)."""
        self.event("fleet", action=action, **fields)
        self.fleet[action] = self.fleet.get(action, 0) + 1

    def log_infomodel(self, action: str = "?", **fields) -> None:
        """Emit one information-model ``infomodel`` event
        (`sbr_tpu.infomodels`: rewire epochs, belief censuses, fixed-point
        solves, closure comparisons, population queries) and fold it into
        the manifest roll-up. Besides the per-action count, two gate
        tallies accumulate: ``nonconverged`` (fixed_point events with
        ``converged=False``) and ``breaches`` (closure events whose
        recorded error exceeds their recorded tolerance) — what
        `report infomodel` exits 1 on."""
        self.event("infomodel", action=action, **fields)
        self.infomodel[action] = self.infomodel.get(action, 0) + 1
        if action == "fixed_point" and fields.get("converged") is False:
            self.infomodel["nonconverged"] = self.infomodel.get("nonconverged", 0) + 1
        if action == "closure":
            err = fields.get("err_aw_sup")
            tol = fields.get("tolerance")
            if (
                isinstance(err, (int, float))
                and isinstance(tol, (int, float))
                and err > tol
            ):
                self.infomodel["breaches"] = self.infomodel.get("breaches", 0) + 1

    def log_audit(self, action: str = "?", **fields) -> None:
        """Emit one numerics-``audit`` event (`sbr_tpu.obs.audit`: canary
        probe verdicts, per-cycle roll-ups, scheduler errors) and fold it
        into the manifest roll-up. Besides the per-action count, the gate
        tallies accumulate: ``drift`` / ``passed`` (probe events by
        verdict) and ``last_cycle`` / ``last_verdict`` (cycle events) —
        what `report audit` exits 1 on."""
        self.event("audit", action=action, **fields)
        self.audit[action] = self.audit.get(action, 0) + 1
        if action == "probe":
            verdict = fields.get("verdict")
            if verdict == "drift":
                self.audit["drift"] = self.audit.get("drift", 0) + 1
            elif verdict == "pass":
                self.audit["passed"] = self.audit.get("passed", 0) + 1
        if action == "cycle":
            if fields.get("cycle") is not None:
                self.audit["last_cycle"] = fields["cycle"]
            if fields.get("verdict") is not None:
                self.audit["last_verdict"] = fields["verdict"]

    def log_demand(self, action: str = "?", **fields) -> None:
        """Emit one workload-``demand`` event (`sbr_tpu.obs.demand`:
        snapshot rotations, advisor-plan writes) and count it per action
        in the manifest roll-up; a plan event's ``fingerprint`` is kept as
        ``last_plan`` so the manifest names the artifact it produced."""
        self.event("demand", action=action, **fields)
        self.demand[action] = self.demand.get(action, 0) + 1
        if action == "plan" and fields.get("fingerprint") is not None:
            self.demand["last_plan"] = fields["fingerprint"]

    def log_prewarm(self, action: str = "?", **fields) -> None:
        """Emit one prefetch-controller ``prewarm`` event
        (`sbr_tpu.serve.prewarm`: plan adoption, tile completion,
        abandonment, plan verdicts) and fold it into the manifest
        roll-up: per-action counts, ``abandoned_<reason>`` tile totals
        (what `report prewarm` gates on for reason "budget"), the last
        plan fingerprint, and the final ``warm``/``tiles`` verdict of a
        completed plan."""
        self.event("prewarm", action=action, **fields)
        self.prewarm[action] = self.prewarm.get(action, 0) + 1
        if action == "abandon":
            reason = str(fields.get("reason") or "unknown")
            key = f"abandoned_{reason}"
            self.prewarm[key] = self.prewarm.get(key, 0) + int(
                fields.get("count") or 1
            )
        if action in ("plan", "plan_done") and fields.get("fingerprint"):
            self.prewarm["last_plan"] = fields["fingerprint"]
        if action == "plan_done":
            for k in ("tiles", "warm", "failed"):
                if fields.get(k) is not None:
                    self.prewarm[f"last_{k}"] = fields[k]

    def log_flight(self, action: str = "?", **fields) -> None:
        """Emit one flight-recorder ``flight`` event (`sbr_tpu.obs.flight`:
        snapshot rotations, the final close write) and fold it into the
        manifest roll-up: per-action counts plus the final snapshot's
        headline utilization numbers as ``last_*`` fields."""
        self.event("flight", action=action, **fields)
        self.flight[action] = self.flight.get(action, 0) + 1
        if action == "final":
            for k in ("records", "dispatches", "dropped_records",
                      "device_busy_frac", "host_gap_frac"):
                if fields.get(k) is not None:
                    self.flight[f"last_{k}"] = fields[k]

    def _resilience_manifest(self) -> Optional[dict]:
        if not any(self.resilience.values()):
            return None
        return {k: v for k, v in self.resilience.items() if v}

    def _elastic_manifest(self) -> Optional[dict]:
        if not any(self.elastic.values()):
            return None
        return {k: v for k, v in self.elastic.items() if v}

    def finalize(self, status: str = "complete") -> None:
        """Write the final manifest and close the event log (idempotent).
        ``status`` lets the graceful-shutdown handler land an
        ``"interrupted"`` manifest instead of ``"complete"``."""
        if self._closed:
            return
        self.event("run_end", n_events=self._n_events, status=status)
        self._write_manifest(status=status)
        self._closed = True
        self._fh.close()
        try:
            # Release the run's trace-span fd (ISSUE 16); the counters were
            # already folded into the manifest above.
            from sbr_tpu.obs import trace as _trace

            _trace.close_for(self.run_dir)
        except Exception:
            pass  # tracing teardown must never sink the run
        if not self._metrics_was_on:
            metrics().disable()
        if self._auto_prune_keep is not None:
            try:
                gc_runs(self.run_dir.parent, self._auto_prune_keep, skip=(self.run_dir,))
            except Exception:
                pass  # retention must never sink the run

    def __enter__(self) -> "RunContext":
        return self

    def __exit__(self, *exc) -> None:
        self.finalize()


# ---------------------------------------------------------------------------
# Module-level API: instrumentation call sites use these; all are no-ops
# (one global read) when no run is active and SBR_OBS is unset.
# ---------------------------------------------------------------------------


def current_run() -> Optional[RunContext]:
    """The active RunContext, auto-starting one if SBR_OBS=1 in the
    environment (checked once per process). None when telemetry is off.
    Env-started runs get a retention budget (SBR_OBS_KEEP, default 32) so
    always-on telemetry cannot grow the run root without bound."""
    global _ENV_CHECKED
    if not _STACK and not _ENV_CHECKED:
        _ENV_CHECKED = True
        if os.environ.get("SBR_OBS", "").strip() not in ("", "0"):
            keep = os.environ.get("SBR_OBS_KEEP", "").strip()
            start_run(
                label=os.environ.get("SBR_OBS_LABEL", "run"),
                auto_prune_keep=int(keep) if keep else 32,
            )
    return _STACK[-1] if _STACK else None


def enabled() -> bool:
    return current_run() is not None


def start_run(
    label: str = "run",
    run_dir: Optional[str] = None,
    root: Optional[str] = None,
    auto_prune_keep: Optional[int] = None,
) -> RunContext:
    """Start (and stack) a run; finalized by `end_run`, `run_context`, or at
    interpreter exit — an abandoned run still lands a complete manifest."""
    global _ENV_CHECKED
    # An explicit run satisfies SBR_OBS's intent; without this, a later
    # empty-stack moment (obs.suspended, or after end_run) would auto-start
    # a surprise second run from the env var.
    _ENV_CHECKED = True
    run = RunContext(run_dir=run_dir, label=label, root=root, auto_prune_keep=auto_prune_keep)
    _STACK.append(run)
    atexit.register(_finalize_if_active, run)
    return run


def _finalize_if_active(run: RunContext) -> None:
    if run in _STACK:
        _STACK.remove(run)
    run.finalize()


def end_run() -> Optional[RunContext]:
    """Finalize and pop the innermost active run."""
    if not _STACK:
        return None
    run = _STACK.pop()
    run.finalize()
    return run


@contextlib.contextmanager
def run_context(label: str = "run", run_dir: Optional[str] = None, root: Optional[str] = None):
    run = start_run(label=label, run_dir=run_dir, root=root)
    try:
        yield run
    finally:
        _finalize_if_active(run)


@contextlib.contextmanager
def suspended():
    """Temporarily disable telemetry for a measurement-critical section.

    The bench harness's steady-state protocols (pipelined dispatch with one
    trailing fence) would be perturbed by `jit_call`'s per-call output fence
    and per-event file IO; inside this context every instrumentation site
    sees no active run and takes its untelemetered path, so measured numbers
    are identical to a telemetry-off process. The run itself stays open —
    events emitted after the block land in the same log."""
    # Resolve any pending SBR_OBS auto-start FIRST: otherwise the first
    # instrumented call inside the block would see an empty stack with
    # _ENV_CHECKED still unset and start a fresh (orphaned) run mid-section.
    current_run()
    saved = _STACK[:]
    _STACK.clear()
    try:
        yield
    finally:
        _STACK[:] = saved


@contextlib.contextmanager
def span(name: str, **attrs):
    """Module-level stage span: delegates to the active run; yields a no-op
    handle (still exposing `.sync`) when telemetry is off or while tracing."""
    run = current_run()
    if run is None or not _trace_clean():
        yield _NULL_SPAN
        return
    with run.span(name, **attrs) as handle:
        yield handle


def event(kind: str, **fields) -> None:
    """Emit one event on the active run (no-op when off or while tracing)."""
    run = current_run()
    if run is not None and _trace_clean():
        run.event(kind, **fields)


def jit_call(name: str, fn, *args):
    """Call jitted ``fn(*args)`` with compile/execute attribution when a run
    is active; otherwise exactly ``fn(*args)``."""
    run = current_run()
    if run is None or not _trace_clean() or not hasattr(fn, "lower"):
        return fn(*args)
    return run.jit_call(name, fn, *args)


def log_status(stage: str, status) -> None:
    """Status-grid accounting event (utils.status codes) for a finished
    sweep/solve. Forces a device→host fetch of the status array — only when
    telemetry is on."""
    run = current_run()
    if run is None or not _trace_clean():
        return
    import numpy as np

    from sbr_tpu.utils.status import status_counts

    arr = np.asarray(status)
    run.event("status", stage=stage, total=int(arr.size), counts=status_counts(arr))


def log_health(stage: str, health, status=None, scenario=None, bank=None) -> None:
    """Numerical-health census event (`sbr_tpu.diag`) for a finished
    sweep/solve: reduces the (possibly per-cell) Health pytree to flag
    counts, divergent-cell count, worst cells, and a residual histogram,
    and folds a roll-up into the run manifest. Forces a device→host fetch
    of the health leaves — only when telemetry is on; a no-op while
    tracing and when ``health`` is None (results assembled outside the
    solvers, e.g. tile checkpoints).

    ``scenario`` / ``bank`` (ISSUE 14): composed-scenario provenance tags.
    They ride the event as explicit fields AND suffix the fold key, so
    `report health` groups per scenario (and per bank in a multi-bank
    contagion run) instead of mixing banks into one census."""
    run = current_run()
    if run is None or health is None or not _trace_clean():
        return
    from sbr_tpu.diag.health import summarize

    summary = summarize(health, status)
    key = stage
    if scenario is not None:
        summary["scenario"] = str(scenario)
        key = f"{key}[{scenario}]"
    if bank is not None:
        summary["bank"] = int(bank)
        key = f"{key}.bank{int(bank)}"
    run.log_health(key, summary)


def log_fault(point: str = "?", kind: str = "?", **fields) -> None:
    """Injected-fault event + manifest roll-up (no-op when telemetry is
    off or while tracing) — the `resilience.faults` emission hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_fault(point, kind, **fields)


def log_retry(scope: str = "?", outcome: str = "?", attempt: int = 0, **fields) -> None:
    """Retry attempt-outcome event + manifest roll-up (no-op when telemetry
    is off or while tracing) — the `resilience.retry` default observer."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_retry(scope, outcome, attempt, **fields)


def log_tile_mem(tile: str = "?", **snap) -> None:
    """Per-tile peak-memory event + manifest roll-up (no-op when telemetry
    is off or while tracing) — the tiled sweep loop's emission hook. With
    no explicit ``snap`` fields, takes a fresh `obs.mem` snapshot."""
    run = current_run()
    if run is None or not _trace_clean():
        return
    if not snap:
        from sbr_tpu.obs import mem

        snap = mem.snapshot()
        if not snap:
            return
    run.log_tile_mem(tile, **snap)


def log_repair(action: str = "?", target: str = "?", ok: bool = True, **fields) -> None:
    """Self-healing repair event + manifest roll-up (no-op when telemetry
    is off or while tracing) — the `resilience.heal` emission hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_repair(action, target, ok, **fields)


def log_scheduler(action: str = "?", **fields) -> None:
    """Elastic-scheduler event + manifest roll-up (no-op when telemetry is
    off or while tracing) — the `resilience.elastic` emission hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_scheduler(action, **fields)


def log_cache(action: str = "?", **fields) -> None:
    """Cross-run tile-cache event + manifest roll-up (no-op when telemetry
    is off or while tracing) — the `resilience.elastic.TileCache` hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_cache(action, **fields)


def log_fleet(action: str = "?", **fields) -> None:
    """Serving-fleet event + manifest roll-up (no-op when telemetry is off
    or while tracing) — the `sbr_tpu.serve` fleet/router emission hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_fleet(action, **fields)


def log_infomodel(action: str = "?", **fields) -> None:
    """Information-model event + manifest roll-up (no-op when telemetry is
    off or while tracing) — the `sbr_tpu.infomodels` emission hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_infomodel(action, **fields)


def log_audit(action: str = "?", **fields) -> None:
    """Numerics-audit event + manifest roll-up (no-op when telemetry is
    off or while tracing) — the `sbr_tpu.obs.audit` emission hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_audit(action, **fields)


def log_demand(action: str = "?", **fields) -> None:
    """Workload-demand event + manifest roll-up (no-op when telemetry is
    off or while tracing) — the `sbr_tpu.obs.demand` emission hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_demand(action, **fields)


def log_prewarm(action: str = "?", **fields) -> None:
    """Prefetch-controller event + manifest roll-up (no-op when telemetry
    is off or while tracing) — the `sbr_tpu.serve.prewarm` emission hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_prewarm(action, **fields)


def log_flight(action: str = "?", **fields) -> None:
    """Flight-recorder event + manifest roll-up (no-op when telemetry is
    off or while tracing) — the `sbr_tpu.obs.flight` emission hook."""
    run = current_run()
    if run is not None and _trace_clean():
        run.log_flight(action, **fields)


def interrupt_all() -> int:
    """Finalize every active run with manifest status ``"interrupted"`` —
    called by the graceful-shutdown handler (`resilience.shutdown`) on
    SIGTERM/SIGINT so a preempted process still leaves honest artifacts.
    Returns how many runs were finalized."""
    n = 0
    while _STACK:
        run = _STACK.pop()
        try:
            run.finalize(status="interrupted")
            n += 1
        except Exception:
            pass  # keep unwinding: one failing finalize must not strand the rest
    return n


def _run_mtime(d: Path) -> float:
    """Recency of a run directory: the newest of the dir and its log files.
    Appending to events.jsonl does NOT touch the directory mtime, so the
    dir stat alone would age a long-running live run into gc range."""
    ts = [d.stat().st_mtime]
    for name in ("events.jsonl", "manifest.json"):
        try:
            ts.append((d / name).stat().st_mtime)
        except OSError:
            pass
    return max(ts)


def _run_is_live(d: Path, grace_s: float) -> bool:
    """Heuristic cross-process liveness: a manifest still in status
    "running" with recent activity belongs to another process's open run —
    deleting it would crash that run's finalize and lose its telemetry. A
    "running" manifest with no activity for ``grace_s`` is a crashed run's
    leftovers and IS collectable."""
    try:
        manifest = json.loads((d / "manifest.json").read_text())
    except (OSError, json.JSONDecodeError):
        return False
    if manifest.get("status") != "running":
        return False
    return (time.time() - _run_mtime(d)) < grace_s


def gc_runs(root, keep: int, skip=(), running_grace_s: float = 6 * 3600.0) -> list:
    """Retention sweep for an obs run root: keep the ``keep`` most recently
    active run directories (dirs holding a ``manifest.json`` — anything
    else is not ours to delete), remove the rest. Never removed: ``skip``
    entries, this process's active runs, and other processes' apparently
    live runs (manifest status "running" with activity within
    ``running_grace_s``). Returns the removed paths."""
    import shutil

    root = Path(root)
    if keep < 0 or not root.is_dir():
        return []
    protected = {Path(p).resolve() for p in skip}
    protected.update(r.run_dir.resolve() for r in _STACK)
    runs = sorted(
        (
            d
            for d in root.iterdir()
            if d.is_dir()
            and (d / "manifest.json").exists()
            and d.resolve() not in protected
            and not _run_is_live(d, running_grace_s)
        ),
        key=_run_mtime,
    )
    doomed = runs[: max(len(runs) - keep, 0)]
    removed = []
    for d in doomed:
        try:
            shutil.rmtree(d)
            removed.append(d)
        except OSError:
            pass  # a concurrently-held run dir is not worth failing over
    return removed


# ---------------------------------------------------------------------------
# AOT helpers
# ---------------------------------------------------------------------------


def _abstract_sig(args) -> tuple:
    """Hashable abstract signature of a pytree of arguments: treedef plus
    (shape, dtype) per array leaf, type+value for hashable scalars."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            sig.append((type(leaf).__name__, leaf if isinstance(leaf, (int, float, bool, str, type(None))) else id(leaf)))
    return (str(treedef), tuple(sig))


def _compiled_info(compiled) -> dict:
    """Static facts about a compiled executable: flop estimate and memory
    footprint from XLA's cost/memory analysis (best-effort per backend)."""
    info: dict = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if isinstance(cost, dict):
            for src, dst in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
                if src in cost:
                    info[dst] = float(cost[src])
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        for attr, key in (
            ("argument_size_in_bytes", "arg_bytes"),
            ("output_size_in_bytes", "out_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("generated_code_size_in_bytes", "code_bytes"),
        ):
            v = getattr(mem, attr, None)
            if v is not None:
                info[key] = int(v)
    except Exception:
        pass
    return info
