"""Stage timing and profiler hooks (SURVEY §5.1) — the low-level timing
primitives of the ``obs`` telemetry subsystem.

Formerly ``sbr_tpu.utils.timing`` (that module now re-exports from here).
The reference stores `solve_time = time() - start` in every result struct
(`src/baseline/learning.jl:110,121`, `src/baseline/solver.jl:414,458`) and
prints per-phase timings inside the fixed-point loop
(`social_learning_solver.jl:129-147`). The TPU equivalents:

- `fence` — an honest device fence: a device→host fetch of a scalar
  reduction, because `block_until_ready` can return before remote execution
  completes on tunneled backends (measured on the axon TPU tunnel; see
  bench.py).
- `StageTimer` — named wall-clock stages over that fence.
- `trace` — context manager around `jax.profiler.trace` for XLA-level
  compile/execute breakdowns viewable in TensorBoard/XProf.

The structured layer on top — `RunContext` event logs, per-stage spans and
AOT compile/execute attribution — lives in `sbr_tpu.obs.runlog`.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict

import jax
import jax.numpy as jnp


def fence(*arrays) -> None:
    """Force completion of the computations producing ``arrays``.

    Fetches a scalar reduction to host — the only fence that is reliable
    across local and tunneled backends.
    """
    acc = jnp.zeros(())
    for a in arrays:
        x = jnp.asarray(a)
        # sum works for float/int/bool; NaN statuses must not poison the
        # fence, hence nansum on floats.
        acc = acc + (jnp.nansum(x) if jnp.issubdtype(x.dtype, jnp.floating) else jnp.sum(x))
    float(acc)


class StageTimer:
    """Accumulates named wall-clock stages.

    Usage::

        timer = StageTimer()
        with timer.stage("learning"):
            ls = solve_learning(params)
            timer.sync(ls.cdf)
        print(timer.report())
    """

    def __init__(self) -> None:
        self.times: Dict[str, float] = {}

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.times[name] = self.times.get(name, 0.0) + time.perf_counter() - t0

    def sync(self, *arrays) -> None:
        fence(*arrays)

    def total(self) -> float:
        return sum(self.times.values())

    def report(self) -> str:
        width = max((len(k) for k in self.times), default=0)
        lines = [f"  {k:<{width}} {v * 1e3:10.1f} ms" for k, v in self.times.items()]
        lines.append(f"  {'total':<{width}} {self.total() * 1e3:10.1f} ms")
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture a `jax.profiler` trace for the enclosed block.

    The trace records compile vs execute time per XLA module — the
    compile-dominated profile of this framework (execution is ms, f64 sweep
    compiles are minutes) is directly visible there.
    """
    with jax.profiler.trace(log_dir, create_perfetto_link=create_perfetto_link):
        yield
