"""Dispatch-pipeline flight recorder (ISSUE 20 tentpole).

Spans (PR 1) time stages serially and traces (PR 16) time requests
end-to-end, but neither can answer the question ROADMAP item 1's
async-dispatch work hangs on: did the DEVICE sit idle while the HOST
formed the next batch? This module records enough to know — a
fixed-capacity ring buffer of (monotonic-ns, stream, kind, tag, seq)
records written lock-free from N handler threads, with paired begin/end
records on three instrumented streams:

- **engine** — admission → queue wait → batch formation → ``dispatch``
  (with an honest device fence: the dispatch span closes only after the
  ``np.asarray`` fetches force the result) → result unpack, per bucket,
  plus ``queue_depth`` / ``occupancy`` / ``shed`` point records.
- **sweeps** — `TileRunner.produce` decomposed into compute vs
  checkpoint-save vs tile-cache I/O per tile (prewarm sweepers included:
  they run the same `TileRunner` in-process).
- **collectives** — the multihost barrier poll and the
  ``exclusive_psum``/psum host launch paths under a mesh.

The ring is lock-free by construction: each record is one immutable
tuple assigned into one list slot (`slots[g % cap] = rec` — a single
bytecode-level store, atomic under CPython), indexed by a global
`itertools.count` whose `next()` is likewise GIL-atomic. Overflow
overwrites oldest; a snapshot copies the slot list and tolerates torn
*pairs* (an end whose begin was overwritten) by dropping them during
`derive_utilization` — no individual record can tear because slots hold
whole tuples, never partial writes.

`derive_utilization` is a PURE fold from a snapshot to the headline
surface: device-busy fraction (union of dispatch spans over the engine
window), host-gap fraction with per-cause attribution (batch formation
vs cache I/O vs admission shed vs queue starvation), queue-depth
percentiles, batch occupancy vs the bucket ladder, and the per-tile
sweep bubble series. That surface rides worker heartbeats, the router
fleet roll-up, ``/metrics`` (``sbr_flight_*``), ``/statz``, a rolling
``flight.json`` next to ``live.json``, and the ``report util`` gate —
the baseline ruler the async-dispatch PR will be measured against
("host-gap fraction drops" on the same bench).

``SBR_FLIGHT=0`` (the default) is a STRUCTURAL no-op in the
audit/demand/prewarm style: this module is never imported by the serving
path, the engine holds no recorder, ``/metrics`` and ``/statz`` stay
byte-free of ``sbr_flight``, zero new XLA traces, answers bit-identical
(regression-tested with a prof trace-count witness).

No jax import anywhere: flight recording is pure host bookkeeping, and
`report util` must run on CI boxes without waking a backend.
"""

from __future__ import annotations

import itertools
import os
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from sbr_tpu.obs.metrics import DEFAULT_LATENCY_BOUNDS_MS, LabeledHistograms

LIVE_SCHEMA = "sbr-flight-live/1"
UTIL_SCHEMA = "sbr-flight-util/1"

#: The three instrumented streams. Per-stream seq counters give pair
#: identity; per-stream labeled histograms give the /metrics latency
#: breakdown by kind.
STREAMS = ("engine", "sweeps", "collectives")

#: Sweep bubble series cap — enough to see the pipeline shape without
#: letting a thousand-tile sweep bloat flight.json.
_MAX_BUBBLES = 64


# ---------------------------------------------------------------------------
# Env knobs
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """Whether the flight recorder is on (``SBR_FLIGHT``; default off —
    and off must be a structural no-op, see the module docstring)."""
    return os.environ.get("SBR_FLIGHT", "").strip() not in ("", "0")


def cap_n() -> int:
    """Ring capacity in records (``SBR_FLIGHT_CAP``, default 4096 slots
    ≈ 2048 spans — a few seconds of busy serving)."""
    env = os.environ.get("SBR_FLIGHT_CAP", "").strip()
    return max(int(env), 8) if env else 4096


def util_floor() -> Optional[float]:
    """The `report util` gate floor (``SBR_FLIGHT_UTIL_FLOOR``):
    device-busy fraction below it exits 1. None = gate disarmed."""
    env = os.environ.get("SBR_FLIGHT_UTIL_FLOOR", "").strip()
    return float(env) if env else None


def min_dispatches() -> int:
    """Minimum dispatches before the floor gate arms
    (``SBR_FLIGHT_MIN_DISPATCHES``, default 3) — a one-dispatch window is
    all compile shadow, not a utilization measurement."""
    env = os.environ.get("SBR_FLIGHT_MIN_DISPATCHES", "").strip()
    return max(int(env), 1) if env else 3


# ---------------------------------------------------------------------------
# Recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Lock-free ring of flight records + per-stream latency histograms.

    Internal slot layout (never serialized as-is):
    ``(g, t_ns, stream, kind, tag, seq, phase, val)`` where ``g`` is the
    global write index (drives overwrite-oldest and the dropped-records
    accounting) and ``phase`` is ``"b"``/``"e"`` for a paired span or
    ``"p"`` for a point record. Every public record path is wrapped in
    try/except: telemetry must never take down serving."""

    def __init__(self, cap: Optional[int] = None,
                 time_fn=time.monotonic) -> None:
        self.cap = max(int(cap), 8) if cap is not None else cap_n()
        self._time = time_fn
        self._reinit()
        self._last_write = 0.0
        self._last_rotate = 0.0
        self._rotations = 0

    def _reinit(self) -> None:
        self._slots: List[Optional[tuple]] = [None] * self.cap
        self._idx = itertools.count()
        self._last_g = -1
        # (last_g, util) memo: heartbeat_block / prometheus_lines /
        # maybe_write all need the derived surface and often fire in the
        # same live-write tick — deriving over the full ring is O(cap),
        # so reuse the result while no new record has landed. Exact, not
        # TTL-stale: any write moves _last_g and misses the memo.
        self._util_memo: Optional[tuple] = None
        self._seq: Dict[str, itertools.count] = {
            s: itertools.count(1) for s in STREAMS
        }
        self._hists: Dict[str, LabeledHistograms] = {
            s: LabeledHistograms(DEFAULT_LATENCY_BOUNDS_MS, max_labels=16)
            for s in STREAMS
        }

    # -- write side ----------------------------------------------------------
    def _put(self, t_ns: int, stream: str, kind: str, tag: str,
             seq: int, phase: str, val) -> None:
        g = next(self._idx)  # GIL-atomic: unique global index per record
        self._last_g = g
        # One atomic store of one immutable tuple — records cannot tear.
        self._slots[g % self.cap] = (g, t_ns, stream, kind, tag, seq,
                                     phase, val)

    def mark(self, stream: str, kind: str, t0_s: float, t1_s: float,
             tag: str = "") -> None:
        """Record one closed span as a begin/end pair sharing a seq.
        Timestamps are `time.monotonic()` seconds (the engine already has
        them in hand at every instrumented site — no double clock reads)."""
        try:
            if t1_s < t0_s:
                t0_s = t1_s
            seq = next(self._seq[stream])
            self._put(int(t0_s * 1e9), stream, kind, tag, seq, "b", None)
            self._put(int(t1_s * 1e9), stream, kind, tag, seq, "e", None)
            self._hists[stream].record(kind, (t1_s - t0_s) * 1e3)
        except Exception:
            pass

    def point(self, stream: str, kind: str, tag: str = "",
              val=None) -> None:
        """Record one instantaneous event (shed, queue depth, occupancy)."""
        try:
            seq = next(self._seq[stream])
            self._put(int(self._time() * 1e9), stream, kind, tag, seq,
                      "p", val)
        except Exception:
            pass

    @contextmanager
    def span(self, stream: str, kind: str, tag: str = ""):
        """``with rec.span("sweeps", "compute", tag=tile_id): ...`` — for
        call sites that don't already hold both timestamps."""
        t0 = self._time()
        try:
            yield
        finally:
            self.mark(stream, kind, t0, self._time(), tag=tag)

    def reset(self) -> None:
        """Drop every record, seq, and histogram (bench warm-up isolation
        and test fixtures — the measured window starts clean)."""
        self._reinit()

    # -- read side -----------------------------------------------------------
    def snapshot(self) -> dict:
        """Copy the ring under active writes. ``writes_total`` is derived
        from the largest global index visible (a lower bound only while
        writes are in flight — exact once writers quiesce), so
        ``dropped_records`` never needs a lock either."""
        slots = list(self._slots)
        recs = [r for r in slots if r is not None]
        top = max((r[0] for r in recs), default=-1)
        writes_total = max(top, self._last_g) + 1
        recs.sort(key=lambda r: (r[1], r[0]))
        return {
            "schema": LIVE_SCHEMA,
            "cap": self.cap,
            "writes_total": writes_total,
            "dropped_records": max(0, writes_total - self.cap),
            "records": [[r[1], r[2], r[3], r[4], r[5], r[6], r[7]]
                        for r in recs],
        }

    def _derived_util(self) -> dict:
        """Memoized ``derive_utilization`` over the current ring: reuse
        the last derived surface while ``_last_g`` is unchanged (an idle
        engine's heartbeats and the paired heartbeat+flight.json writes
        of one live tick), re-derive the moment any record lands."""
        memo = self._util_memo
        g = self._last_g
        if memo is not None and memo[0] == g:
            return memo[1]
        util = derive_utilization(self.snapshot())
        self._util_memo = (g, util)
        return util

    def heartbeat_block(self) -> dict:
        """The compact util block riding worker heartbeats (what the
        router folds into the fleet utilization surface)."""
        util = self._derived_util()
        return {
            "device_busy_frac": util.get("device_busy_frac"),
            "host_gap_frac": util.get("host_gap_frac"),
            "dispatches": util.get("dispatches", 0),
            "queue_p99": (util.get("queue_depth") or {}).get("p99"),
            "dropped_records": util.get("dropped_records", 0),
            "records": util.get("records", 0),
        }

    def prometheus_lines(self) -> list:
        """``sbr_flight_*`` exposition. SBR_FLIGHT=0 engines contribute
        NOTHING (the recorder doesn't exist) — tests assert the exposition
        is byte-free of the prefix when flight is off."""
        util = self._derived_util()
        busy = util.get("device_busy_frac")
        gap = util.get("host_gap_frac")
        lines = [
            "# TYPE sbr_flight_records gauge",
            f"sbr_flight_records {util.get('records', 0)}",
            "# TYPE sbr_flight_dropped_records counter",
            f"sbr_flight_dropped_records {util.get('dropped_records', 0)}",
            "# TYPE sbr_flight_dispatches gauge",
            f"sbr_flight_dispatches {util.get('dispatches', 0)}",
            "# TYPE sbr_flight_device_busy_frac gauge",
            f"sbr_flight_device_busy_frac "
            f"{busy if busy is not None else 0:g}",
            "# TYPE sbr_flight_host_gap_frac gauge",
            f"sbr_flight_host_gap_frac {gap if gap is not None else 0:g}",
        ]
        for s in STREAMS:
            lines.extend(
                self._hists[s].to_prometheus(f"sbr_flight_{s}_ms",
                                             label_key="kind")
            )
        return lines

    # -- rolling snapshot ----------------------------------------------------
    def _rotate_s(self) -> float:
        env = os.environ.get("SBR_FLIGHT_ROTATE_S", "").strip()
        return float(env) if env else 0.0

    def maybe_write(self, run, min_interval_s: float = 0.5,
                    force: bool = False) -> bool:
        """Write the rolling ``flight.json`` through ``run.live_snapshot``
        at a bounded cadence (``force`` for the final write at engine
        close). The document carries both the raw ring (``records``) and
        the derived ``util`` surface so `report util` works even against
        a snapshot from a newer/older deriver. With ``SBR_FLIGHT_ROTATE_S``
        set, the previous snapshot is archived as ``flight.NNN.json``
        before each rotation-due overwrite (what ``report gc
        --flight-keep`` prunes). Never raises."""
        if run is None:
            return False
        now = self._time()
        if not force and now - self._last_write < min_interval_s:
            return False
        self._last_write = now
        try:
            rotate_s = self._rotate_s()
            if rotate_s > 0 and now - self._last_rotate >= rotate_s:
                self._archive_snapshot(run)
                self._last_rotate = now
            g = self._last_g
            doc = self.snapshot()
            util = derive_utilization(doc)
            doc["util"] = util
            # Seed the memo: the heartbeat/exposition reader of this
            # same tick reuses the derive paid here.
            self._util_memo = (g, util)
            doc["ts"] = round(time.time(), 3)
            run.live_snapshot(doc, name="flight.json")
            if force:
                util = doc["util"]
                try:
                    run.log_flight(
                        "final",
                        records=util.get("records", 0),
                        dispatches=util.get("dispatches", 0),
                        dropped_records=util.get("dropped_records", 0),
                        device_busy_frac=util.get("device_busy_frac"),
                        host_gap_frac=util.get("host_gap_frac"),
                    )
                except Exception:
                    pass
            return True
        except Exception:
            return False

    def _archive_snapshot(self, run) -> None:
        """Archive the active ``flight.json`` as the next free
        ``flight.NNN.json`` (rotation — the gc candidates)."""
        active = Path(run.run_dir) / "flight.json"
        if not active.exists():
            return
        idx = self._rotations
        while (Path(run.run_dir) / f"flight.{idx:03d}.json").exists():
            idx += 1
        (Path(run.run_dir) / f"flight.{idx:03d}.json").write_bytes(
            active.read_bytes()
        )
        self._rotations = idx + 1
        try:
            run.log_flight("rotate", index=idx)
        except Exception:
            pass

    def close(self, run) -> None:
        """Final force-write at engine/sweeper close."""
        self.maybe_write(run, force=True)


# ---------------------------------------------------------------------------
# Process-wide recorder
# ---------------------------------------------------------------------------

_SHARED: Optional[FlightRecorder] = None


def shared() -> FlightRecorder:
    """The process-wide recorder. The engine, the sweep tile loop, and
    the collectives host paths all write here, so one ``flight.json``
    shows engine/sweeps/collectives on one monotonic timeline (a prewarm
    sweeper inside a serving process lands its tile spans next to the
    dispatches it's hiding behind)."""
    global _SHARED
    if _SHARED is None:
        _SHARED = FlightRecorder()
    return _SHARED


def reset_shared() -> None:
    """Drop the process-wide recorder (tests re-enter with a fresh cap)."""
    global _SHARED
    _SHARED = None


# ---------------------------------------------------------------------------
# Pure derivation: snapshot -> utilization surface
# ---------------------------------------------------------------------------


def _union(intervals: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Merge possibly-overlapping (t0, t1) intervals into a sorted
    disjoint union."""
    out: List[Tuple[int, int]] = []
    for t0, t1 in sorted(intervals):
        if out and t0 <= out[-1][1]:
            if t1 > out[-1][1]:
                out[-1] = (out[-1][0], t1)
        else:
            out.append((t0, t1))
    return out


def _overlap_ns(union: List[Tuple[int, int]], g0: int, g1: int) -> int:
    """Total length of ``union`` falling inside [g0, g1]."""
    total = 0
    for t0, t1 in union:
        lo, hi = max(t0, g0), min(t1, g1)
        if hi > lo:
            total += hi - lo
    return total


def _pct(samples: List[float], p: float) -> float:
    s = sorted(samples)
    return s[min(int(p / 100.0 * len(s)), len(s) - 1)]


def derive_utilization(snap: dict) -> dict:
    """PURE fold from a ring snapshot to the utilization surface — no
    clock reads, no I/O, so `report util` and tests can replay canned
    snapshots deterministically.

    Attribution walks each host gap (the complement of the dispatch-span
    union inside the engine window) and splits it by overlap priority:
    batch-formation spans first, then cache I/O, and the unexplained
    remainder is admission shed (if a shed point landed in the gap) or
    queue starvation (nothing to run). Torn pairs — an end whose begin
    was overwritten, or vice versa — are counted in ``unpaired`` and
    otherwise ignored."""
    rows = []
    for r in snap.get("records") or []:
        try:
            t_ns, stream, kind, tag, seq, phase, val = r
            rows.append((int(t_ns), str(stream), str(kind), str(tag or ""),
                         int(seq), str(phase), val))
        except Exception:
            continue  # malformed row (hand-edited snapshot) — skip
    spans: Dict[str, List[Tuple[int, int, str, str]]] = {}
    points: Dict[str, List[Tuple[int, str, str, object]]] = {}
    begins: Dict[tuple, Tuple[int, str]] = {}
    unpaired = 0
    for t_ns, stream, kind, tag, seq, phase, val in sorted(rows):
        if phase == "p":
            points.setdefault(stream, []).append((t_ns, kind, tag, val))
        elif phase == "b":
            begins[(stream, kind, seq)] = (t_ns, tag)
        elif phase == "e":
            b = begins.pop((stream, kind, seq), None)
            if b is None:
                unpaired += 1
                continue
            t0, tag0 = b
            if t_ns >= t0:
                spans.setdefault(stream, []).append((t0, t_ns, kind, tag0))
    unpaired += len(begins)

    out = {
        "schema": UTIL_SCHEMA,
        "records": len(rows),
        "dropped_records": int(snap.get("dropped_records") or 0),
        "unpaired": unpaired,
        "dispatches": 0,
        "window_s": None,
        "device_busy_frac": None,
        "host_gap_frac": None,
        "gap_causes": {},
    }

    # -- engine stream -------------------------------------------------------
    eng = spans.get("engine", [])
    eng_points = points.get("engine", [])
    times = [t for t0, t1, _, _ in eng for t in (t0, t1)]
    times.extend(t for t, _, _, _ in eng_points)
    dispatch = [(t0, t1) for t0, t1, k, _ in eng if k == "dispatch"]
    out["dispatches"] = len(dispatch)
    if times and max(times) > min(times):
        w0, w1 = min(times), max(times)
        window_ns = w1 - w0
        busy = _union(dispatch)
        busy_ns = sum(t1 - t0 for t0, t1 in busy)
        out["window_s"] = round(window_ns / 1e9, 6)
        out["device_busy_frac"] = round(
            min(busy_ns / window_ns, 1.0), 4)
        out["host_gap_frac"] = round(1.0 - out["device_busy_frac"], 4)
        # Gaps: complement of the busy union inside the window.
        gaps: List[Tuple[int, int]] = []
        cursor = w0
        for t0, t1 in busy:
            if t0 > cursor:
                gaps.append((cursor, t0))
            cursor = max(cursor, t1)
        if cursor < w1:
            gaps.append((cursor, w1))
        batch_u = _union([(t0, t1) for t0, t1, k, _ in eng if k == "batch"])
        cache_u = _union([(t0, t1) for t0, t1, k, _ in eng if k == "cache"])
        sheds = [t for t, k, _, _ in eng_points if k == "shed"]
        causes = {"batch_formation": 0, "cache_io": 0,
                  "admission_shed": 0, "queue_starvation": 0}
        for g0, g1 in gaps:
            glen = g1 - g0
            bf = min(_overlap_ns(batch_u, g0, g1), glen)
            ci = min(_overlap_ns(cache_u, g0, g1), glen - bf)
            rem = glen - bf - ci
            causes["batch_formation"] += bf
            causes["cache_io"] += ci
            if rem > 0:
                if any(g0 <= t <= g1 for t in sheds):
                    causes["admission_shed"] += rem
                else:
                    causes["queue_starvation"] += rem
        gap_ns = sum(g1 - g0 for g0, g1 in gaps)
        out["gap_causes"] = {
            c: {"s": round(ns / 1e9, 6),
                "frac": round(ns / gap_ns, 4) if gap_ns else 0.0}
            for c, ns in causes.items() if ns > 0
        }
    depth = [float(v) for t, k, _, v in eng_points
             if k == "queue_depth" and v is not None]
    if depth:
        out["queue_depth"] = {
            "p50": _pct(depth, 50), "p95": _pct(depth, 95),
            "p99": _pct(depth, 99), "max": max(depth),
            "samples": len(depth),
        }
    occ = [(tag, float(v)) for t, k, tag, v in eng_points
           if k == "occupancy" and v is not None]
    if occ:
        by_bucket: Dict[str, List[float]] = {}
        for tag, v in occ:
            by_bucket.setdefault(tag or "?", []).append(v)
        out["occupancy"] = {
            "mean": round(sum(v for _, v in occ) / len(occ), 4),
            "by_bucket": {
                b: round(sum(vs) / len(vs), 4)
                for b, vs in sorted(by_bucket.items())
            },
        }
    shed_tags: Dict[str, int] = {}
    for t, k, tag, _ in eng_points:
        if k == "shed":
            shed_tags[tag or "?"] = shed_tags.get(tag or "?", 0) + 1
    if shed_tags:
        out["sheds"] = dict(sorted(shed_tags.items()))

    # -- sweeps stream -------------------------------------------------------
    sw = spans.get("sweeps", [])
    if sw:
        by_kind: Dict[str, int] = {}
        tiles: Dict[str, Tuple[int, int]] = {}
        for t0, t1, k, tag in sw:
            by_kind[k] = by_kind.get(k, 0) + (t1 - t0)
            tid = tag or "?"
            lo, hi = tiles.get(tid, (t0, t1))
            tiles[tid] = (min(lo, t0), max(hi, t1))
        ordered = sorted(tiles.values())
        bubbles = []
        for (_, prev_hi), (nxt_lo, _) in zip(ordered, ordered[1:]):
            if nxt_lo > prev_hi:
                bubbles.append(round((nxt_lo - prev_hi) / 1e6, 3))
        out["sweeps"] = {
            "tiles": len(tiles),
            "by_kind_ms": {k: round(ns / 1e6, 3)
                           for k, ns in sorted(by_kind.items())},
            "bubbles_ms": bubbles[:_MAX_BUBBLES],
            "bubble_total_ms": round(sum(bubbles), 3),
        }

    # -- collectives stream --------------------------------------------------
    col = spans.get("collectives", [])
    col_points = points.get("collectives", [])
    if col or col_points:
        agg: Dict[str, dict] = {}
        for t0, t1, k, _ in col:
            a = agg.setdefault(k, {"count": 0, "total_ms": 0.0})
            a["count"] += 1
            a["total_ms"] += (t1 - t0) / 1e6
        for t, k, _, _ in col_points:
            a = agg.setdefault(k, {"count": 0, "total_ms": 0.0})
            a["count"] += 1
        out["collectives"] = {
            k: {"count": a["count"], "total_ms": round(a["total_ms"], 3)}
            for k, a in sorted(agg.items())
        }
    return out


# ---------------------------------------------------------------------------
# Retention (report gc --flight-keep)
# ---------------------------------------------------------------------------


def gc_flight_files(root, keep: int = 4,
                    running_grace_s: float = 6 * 3600.0) -> list:
    """Prune rotated flight snapshots (``flight.NNN.json``) inside each
    run dir under ``root`` down to the newest ``keep``, mirroring the
    ``--demand-keep`` / ``--prewarm-keep`` contract: live runs (manifest
    "running" with recent mtime) are never touched, and the ACTIVE
    ``flight.json`` is never a candidate (the glob requires the
    rotation's second dot). Returns removed paths."""
    from sbr_tpu.obs import runlog

    keep = max(int(keep), 0)
    removed: list = []
    root = Path(root)
    if not root.is_dir():
        return removed
    for d in sorted(p for p in root.iterdir() if p.is_dir()):
        if runlog._run_is_live(d, running_grace_s):
            continue
        rotated = sorted(d.glob("flight.*.json"))
        for path in rotated[: max(len(rotated) - keep, 0)]:
            try:
                path.unlink()
                removed.append(str(path))
            except OSError:
                pass
    return removed
