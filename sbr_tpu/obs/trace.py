"""Distributed request tracing for the serving stack (ISSUE 16 tentpole).

A query that enters the fleet crosses router -> worker endpoint -> engine
admission -> micro-batcher -> LRU/disk/tile-cache layers -> dispatch.  This
module gives every hop a span so `report trace` can join the whole trip into
a per-query waterfall and `report slo` can say *which* layer ate the budget
when p99 breaches.

Design (mirrors the `runlog` discipline, but per-query instead of per-run):

- The router (or a direct endpoint hit) mints a 16-hex trace id and
  propagates it via the ``X-SBR-Trace-Id`` header; the parent span id for
  the remote child rides ``X-SBR-Parent-Span``.  Header presence == the
  minting side decided to sample, so workers honour it unconditionally and
  cross-process joins never dangle on a sampling disagreement.
- ``TraceContext`` is the lock-free per-thread buffer: each in-flight query
  owns one context, spans accumulate via plain ``list.append`` (atomic under
  CPython, so hedge threads can contribute without a lock), and nothing is
  written until the root owner calls ``TraceWriter.commit``.
- ``TraceWriter`` appends whole JSON lines to ``trace.jsonl`` in the run
  directory with a single ``os.write`` on an ``O_APPEND`` fd per trace —
  the same whole-line atomic-append discipline ``events.jsonl`` uses, so a
  kill -9 can tear at most the final line and readers tolerate it
  (``bad_span_lines``, same contract as ``bad_event_lines``).
- Sampling: ``SBR_TRACE_SAMPLE`` in [0, 1].  0 (the default) is *hard off*:
  ``mint`` returns ``None``, every instrumentation site is a ``None`` check,
  no header is added, and answers are bit-identical to an untraced build.
  For 0 < rate < 1 the keep decision is a deterministic hash of the trace id
  so router and workers agree without coordination; queries that breach the
  locally resolved SLO are *always* committed (``exemplar: true``) so tail
  latency always has a waterfall even at low sample rates.
- Zero XLA-trace impact: spans are recorded purely in host code at the same
  boundaries the existing obs events already use; nothing here runs under a
  `jax.jit` trace (witnessed by the `prof.trace_counts` registry staying
  flat in tests).

Span record schema (one JSON object per line)::

    {"trace": "9f2c...", "span": "a1b2c3d4", "parent": "..."|null,
     "name": "router.forward", "svc": "router", "ts": <wall s>,
     "dur_ms": 3.21, ...free-form attrs..., "exemplar": true?}

This module is deliberately jax-free so the router and `report` stay
importable without an accelerator runtime.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from sbr_tpu.obs.metrics import DEFAULT_LATENCY_BOUNDS_MS, LabeledHistograms

# Wire protocol -------------------------------------------------------------

TRACE_HEADER = "X-SBR-Trace-Id"
PARENT_HEADER = "X-SBR-Parent-Span"

#: Active span file name inside a run dir; rotated siblings match
#: ``trace.NNN.jsonl`` (see :meth:`TraceWriter._maybe_rotate`).
TRACE_FILE = "trace.jsonl"

_RESERVED_KEYS = ("trace", "span", "parent", "name", "svc", "ts", "dur_ms")


def sample_rate() -> float:
    """Resolved ``SBR_TRACE_SAMPLE`` in [0, 1]; 0 (default) disables tracing."""
    raw = os.environ.get("SBR_TRACE_SAMPLE", "").strip()
    if not raw:
        return 0.0
    try:
        rate = float(raw)
    except ValueError:
        return 0.0
    return min(max(rate, 0.0), 1.0)


def slo_ms() -> Optional[float]:
    """Resolved ``SBR_SERVE_SLO_MS`` (jax-free twin of ``engine.slo_ms``)."""
    raw = os.environ.get("SBR_SERVE_SLO_MS", "").strip()
    if not raw:
        return None
    try:
        val = float(raw)
    except ValueError:
        return None
    return val if val > 0 else None


def max_file_bytes() -> int:
    """Rotation threshold for ``trace.jsonl`` (``SBR_TRACE_MAX_MB``, default 64)."""
    raw = os.environ.get("SBR_TRACE_MAX_MB", "").strip()
    try:
        mb = float(raw) if raw else 64.0
    except ValueError:
        mb = 64.0
    return max(int(mb * 1024 * 1024), 1 << 16)


def new_trace_id() -> str:
    return os.urandom(8).hex()


_HASH_SPACE = float(0xFFFFFFFF + 1)


def keep_decision(trace_id: str, rate: float) -> bool:
    """Deterministic head-sampling verdict shared by every process.

    Hashing the id (rather than rolling a die per process) means the router
    and each worker reach the same keep/drop answer for the same trace, so a
    kept trace is never half-written.
    """
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    try:
        bucket = int(trace_id[:8], 16)
    except ValueError:
        return True  # un-parseable foreign id: keep rather than orphan
    return bucket / _HASH_SPACE < rate


class TraceContext:
    """Per-query span buffer. One context per in-flight request.

    Spans accumulate with ``list.append`` — atomic under CPython — so the
    request thread and hedge threads can both contribute without a lock.
    Nothing is persisted until the root owner calls ``TraceWriter.commit``.
    """

    __slots__ = ("trace_id", "keep", "remote_parent", "parent_id", "service", "spans")

    def __init__(
        self,
        trace_id: str,
        keep: bool = True,
        remote_parent: Optional[str] = None,
        service: str = "?",
    ) -> None:
        self.trace_id = trace_id
        self.keep = keep
        #: Parent span id received over the wire (the router's forward span).
        self.remote_parent = remote_parent
        #: Parent id the *next* layer down should attach to; the owner of the
        #: root span sets this before handing the context to the engine.
        self.parent_id = remote_parent
        self.service = service
        self.spans: List[dict] = []

    def alloc_id(self) -> str:
        return os.urandom(4).hex()

    def add(
        self,
        name: str,
        t0: float,
        dur_s: float,
        parent: Optional[str] = None,
        span_id: Optional[str] = None,
        **attrs,
    ) -> str:
        """Record one completed span; returns its id.

        ``t0`` is a wall-clock start (seconds since epoch) so spans written
        by different processes join on a shared axis; ``dur_s`` is measured
        with the monotonic clock by the caller.
        """
        sid = span_id if span_id is not None else self.alloc_id()
        rec = {
            "trace": self.trace_id,
            "span": sid,
            "parent": parent,
            "name": name,
            "svc": self.service,
            "ts": round(t0, 6),
            "dur_ms": round(max(dur_s, 0.0) * 1e3, 4),
        }
        for k, v in attrs.items():
            if k not in _RESERVED_KEYS and v is not None:
                rec[k] = v
        self.spans.append(rec)
        return sid


def mint(service: str) -> Optional[TraceContext]:
    """Mint a new trace, or ``None`` when tracing is off (the common case).

    A single env read and a float compare when disabled — the zero-overhead
    contract every other obs layer already honours.
    """
    rate = sample_rate()
    if rate <= 0.0:
        return None
    tid = new_trace_id()
    return TraceContext(tid, keep=keep_decision(tid, rate), service=service)


def from_headers(
    trace_id, parent_id=None, service: str = "worker"
) -> Optional[TraceContext]:
    """Adopt an inbound trace header, or mint locally on a direct hit.

    Header presence wins over the local sample rate: the minting side
    already decided to keep this trace, and honouring that is what makes
    cross-process joins complete.
    """
    if trace_id:
        tid = str(trace_id).strip()[:64]
        parent = str(parent_id).strip()[:64] if parent_id else None
        return TraceContext(tid, keep=True, remote_parent=parent, service=service)
    return mint(service)


# Per-layer span-duration histograms (exposed on /metrics) -------------------

_LAYER_HISTOGRAMS = LabeledHistograms(DEFAULT_LATENCY_BOUNDS_MS)


def layer_histograms() -> LabeledHistograms:
    """Process-global per-layer span-duration histograms (committed spans only)."""
    return _LAYER_HISTOGRAMS


def layer_prometheus() -> List[str]:
    """Prometheus exposition lines for the per-layer span histograms."""
    return _LAYER_HISTOGRAMS.to_prometheus("sbr_trace_span_ms", label_key="layer")


# Writer --------------------------------------------------------------------


class TraceWriter:
    """Span sink for one run directory (``trace.jsonl``).

    Each commit encodes the context's spans into one newline-terminated blob
    and lands it with a single ``os.write`` on an ``O_APPEND`` fd, so lines
    from concurrent commits (threads or processes sharing the dir) interleave
    at line granularity only.  Rotation renames the active file to
    ``trace.NNN.jsonl``; a racing write that lands on the just-rotated inode
    still reaches readers because ``load_spans`` reads rotated files too.
    """

    def __init__(self, run_dir) -> None:
        self.run_dir = Path(run_dir)
        self.path = self.run_dir / TRACE_FILE
        self._fd: Optional[int] = None
        self._rotate_lock = threading.Lock()
        self.counters = {"traces": 0, "spans": 0, "exemplars": 0, "dropped": 0}

    def _ensure_fd(self) -> Optional[int]:
        if self._fd is None:
            try:
                self.run_dir.mkdir(parents=True, exist_ok=True)
                self._fd = os.open(
                    str(self.path), os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
                )
            except OSError:
                return None
        return self._fd

    def _maybe_rotate(self, incoming: int) -> None:
        fd = self._fd
        if fd is None:
            return
        try:
            size = os.fstat(fd).st_size
        except OSError:
            return
        if size + incoming <= max_file_bytes():
            return
        with self._rotate_lock:
            if self._fd is not fd:  # another thread already rotated
                return
            n = len(list(self.run_dir.glob("trace.*.jsonl"))) + 1
            rotated = self.run_dir / f"trace.{n:03d}.jsonl"
            try:
                os.replace(str(self.path), str(rotated))
                os.close(fd)
            except OSError:
                return
            self._fd = None

    def commit(self, ctx: Optional[TraceContext], exemplar: bool = False) -> bool:
        """Persist (or drop) a finished trace's spans.

        ``exemplar=True`` forces the write even when the head-sampling
        verdict said drop — the SLO-breach tail always keeps its waterfall.
        Returns True when spans were written.
        """
        if ctx is None or not ctx.spans:
            return False
        if not ctx.keep and not exemplar:
            self.counters["dropped"] += 1
            return False
        mark = exemplar and not ctx.keep
        lines = []
        for rec in ctx.spans:
            if mark:
                rec = dict(rec, exemplar=True)
            lines.append(json.dumps(rec, separators=(",", ":")))
            _LAYER_HISTOGRAMS.record(rec["name"], rec["dur_ms"])
        blob = ("\n".join(lines) + "\n").encode("utf-8")
        self._maybe_rotate(len(blob))
        fd = self._ensure_fd()
        if fd is None:
            return False
        try:
            os.write(fd, blob)
        except OSError:
            return False
        self.counters["traces"] += 1
        self.counters["spans"] += len(lines)
        if mark:
            self.counters["exemplars"] += 1
        return True

    def close(self) -> Dict[str, int]:
        fd, self._fd = self._fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
        return dict(self.counters)


_WRITERS: Dict[str, TraceWriter] = {}
_WRITERS_LOCK = threading.Lock()


def writer_for(run) -> Optional[TraceWriter]:
    """Singleton :class:`TraceWriter` for a run's directory (or ``None``).

    Accepts a ``RunContext`` (anything with ``run_dir``) or a path.  Returns
    ``None`` when there is no run directory to write into — tracing requires
    a run dir, exactly like every other obs stream.
    """
    if run is None:
        return None
    run_dir = getattr(run, "run_dir", run)
    try:
        key = str(Path(run_dir).resolve())
    except OSError:
        key = str(run_dir)
    with _WRITERS_LOCK:
        w = _WRITERS.get(key)
        if w is None:
            w = TraceWriter(run_dir)
            _WRITERS[key] = w
        return w


def close_for(run_dir) -> Optional[Dict[str, int]]:
    """Close (and forget) the writer for ``run_dir``; returns its counters."""
    try:
        key = str(Path(run_dir).resolve())
    except OSError:
        key = str(run_dir)
    with _WRITERS_LOCK:
        w = _WRITERS.pop(key, None)
    return w.close() if w is not None else None


def summary_for(run_dir) -> Optional[Dict[str, int]]:
    """Live counter snapshot for ``run_dir``'s writer (manifest roll-up)."""
    try:
        key = str(Path(run_dir).resolve())
    except OSError:
        key = str(run_dir)
    with _WRITERS_LOCK:
        w = _WRITERS.get(key)
    return dict(w.counters) if w is not None else None


# Reading (report side; same torn-line tolerance as events.jsonl) ------------


def trace_files(run_dir) -> List[Path]:
    """Active + rotated span files for a run dir, oldest first."""
    d = Path(run_dir)
    rotated = sorted(d.glob("trace.*.jsonl"))
    active = d / TRACE_FILE
    return rotated + ([active] if active.exists() else [])


def load_spans(run_dir) -> Tuple[List[dict], int]:
    """Read every span line in a run dir; returns ``(spans, bad_span_lines)``.

    Byte-level read with ``errors="replace"`` decoding: a torn final line
    (kill -9 mid-append) or interleaved garbage is counted, never fatal —
    the ``bad_event_lines`` contract, applied to spans.
    """
    spans: List[dict] = []
    bad = 0
    for path in trace_files(run_dir):
        try:
            raw = path.read_bytes()
        except OSError:
            continue
        for line in raw.decode("utf-8", errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                bad += 1
                continue
            if not isinstance(rec, dict) or "trace" not in rec or "span" not in rec:
                bad += 1
                continue
            spans.append(rec)
    return spans, bad


# GC ------------------------------------------------------------------------


def gc_trace_files(
    root, keep_rotated: int = 1, running_grace_s: float = 6 * 3600.0
) -> List[str]:
    """Prune rotated trace span files under an obs root; returns removed paths.

    Run directories that look live (manifest ``status: running`` with recent
    mtime — the same test ``gc_runs`` applies) are never touched, and the
    active ``trace.jsonl`` is never removed here: whole-dir retention stays
    ``gc_runs``'s job, this only bounds the rotated-file tail inside kept
    dirs under ``SBR_OBS_KEEP``.
    """
    from sbr_tpu.obs import runlog  # local import: avoid a cycle at import time

    removed: List[str] = []
    rootp = Path(root)
    if not rootp.is_dir():
        return removed
    for run_dir in rootp.iterdir():
        if not run_dir.is_dir() or not (run_dir / "manifest.json").exists():
            continue
        if runlog._run_is_live(run_dir, running_grace_s):
            continue
        rotated = sorted(
            run_dir.glob("trace.*.jsonl"), key=lambda p: p.stat().st_mtime
        )
        excess = rotated[: max(len(rotated) - max(keep_rotated, 0), 0)]
        for path in excess:
            try:
                path.unlink()
                removed.append(str(path))
            except OSError:
                continue
    return removed
