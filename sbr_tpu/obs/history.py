"""Append-only performance history + trend/regression gating (ISSUE 3).

The repo accumulates a perf trajectory on disk (``BENCH_r*.json``, obs run
dirs) that nothing aggregates or gates — a silent 2× regression in
``beta_u_grid_equilibria_per_sec`` would merge without a red signal. This
module is the missing memory: every bench/sweep run appends one line of
headline metrics to an append-only ``bench_history.jsonl`` (path from
``SBR_OBS_HISTORY``, default ``benchmarks/bench_history.jsonl``), and the
``report trend`` CLI renders per-metric timelines (sparkline + rolling-
median baseline) and gates:

    python -m sbr_tpu.obs.report trend [HISTORY]            # timelines
    python -m sbr_tpu.obs.report trend --check --tolerance 0.15
    # exit 0 flat/improving · 1 regression beyond tolerance · 3 missing or
    # short history (a gate with nothing to compare must not pass silently)

Regression semantics: the LATEST record is compared per metric against the
rolling median of up to ``--window`` prior records from the SAME platform
(a cpu-fallback bench must never read as a 100× tpu regression). Metric
polarity is inferred from the name: ``*_per_sec``/throughput counts are
higher-better; ``*_s`` durations, byte counts, and divergent-cell counts
are lower-better. A lower-better metric whose baseline is 0 regresses on
ANY increase (the health gate shape: one divergent cell is a signal, not a
percentage).

No jax import anywhere in this module — the trend gate runs on CI boxes
and bench parents that must never wake an accelerator backend (same
contract as ``obs.report``).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from pathlib import Path
from typing import Optional

# Record schema: 2 added memory metrics (mem_peak_bytes and the per-workload
# grid/agents peaks from the bench child — ISSUE 5); 3 adds the serving
# workload's latency/cache metrics (serve_p50_ms / serve_p99_ms /
# serve_cache_hit_rate — ISSUE 7); 4 adds the tiled-sweep workload's
# cold/warm throughput + warm-cache hit rate (sweep_cold_cells_per_sec /
# sweep_warm_cells_per_sec / sweep_warm_hit_rate); the elastic scheduler's
# per-host ``elastic_cells_per_sec`` records live in a SIDECAR file
# (``<history>.elastic.jsonl`` — the trend gate evaluates only the latest
# main-history record, so cost-model records must not displace bench
# lines) and seed `resilience.elastic.seed_rate_from_history` (ISSUE 8);
# 5 adds the adaptive-numerics split (ISSUE 9): grid_adaptive_speedup
# (adaptive vs bit-exact fixed control, timed back-to-back on the same
# shape) and grid_mean_effective_iters (mean per-cell root-find iterations
# from the Health grid — the fixed path records its constant budget).
# 6 adds the mega-scale agents generation split (ISSUE 10):
# agents_graph_build_s (steady on-device canonical-layout build),
# agents_graph_gen_edges_per_sec (generation throughput) and
# agents_graph_gen_speedup (device generator vs the host-numpy pipeline at
# the 10^7-edge control shape), so `report trend` gates the generation
# path separately from step throughput.
# 7 adds the serving-fleet SLO split (ISSUE 11, the multi-process
# `loadgen --fleet` bench): fleet_p99_ms (client-observed measured-phase
# p99 through the router — lower-better), fleet_failover_count
# (re-dispatches after forward failures — lower-better by the _count
# rule), and fleet_shed_rate (fraction of queries shed at admission —
# lower-better by the shed rule).
# 8 adds the differentiable-equilibria workload (ISSUE 13, bench.py
# bench_grad): grads_per_sec (IFT sensitivity-surface throughput —
# partial derivatives per second through the vmapped value-and-grad
# program; higher-better by the per_sec rule) and calib_steps_per_sec
# (calibration Adam steps per second over the jitted IFT loss;
# higher-better likewise).
# 9 adds the composable-scenario workload (ISSUE 14, bench.py
# bench_scenario): scenario_overhead_ratio (composed-baseline grid steady
# time over the legacy grid program's on the same shape — lower-better by
# the overhead rule; ~1.0 means the composition layer is free) and
# scenario_multibank_cells_per_sec (bank-cells per second through the
# contagion loop, dispatches × banks / wall — higher-better by the
# per_sec rule).
# 10 adds the information-model workload (ISSUE 15, bench.py
# bench_infomodels): infomodel_belief_updates_per_sec (fused Bayesian
# belief-update throughput through the observer kernel — agent-steps per
# second of the bayes channel; higher-better by the per_sec rule) and
# infomodel_population_queries_per_sec (end-to-end population what-if
# queries per second at the query shape — fixed point + S member sims +
# crossing reduction; higher-better likewise).
# Schema 11 adds the numerics-audit workload (bench.py bench_audit):
# audit_probes_per_sec (golden-battery probe throughput — how fast the
# canary battery turns over; higher-better by the per_sec rule) and
# audit_overhead_ratio (serve-loop steady-state latency with the idle-gated
# audit scheduler enabled over the audit-off control; lower-better by the
# overhead rule — ~1.0 means canaries are invisible to the hot path).
# Schema 12 adds the workload-demand observatory (bench.py bench_demand):
# demand_updates_per_sec (DemandTracker streaming-record throughput —
# histogram bin + Misra-Gries sketch update per query; higher-better by
# the per_sec rule) and demand_merge_ms (one fleet merge of the workers'
# heartbeat demand surfaces at the router; lower-better by the _ms rule).
# Schema 13 adds the self-healing prefetch workload (bench.py
# bench_prewarm): prewarm_warm_hit_rate (fraction of hot-region queries a
# breaker-open outage answers from prefetched tiles — higher-better by
# the hit_rate rule), prewarm_outage_p99_ms (p99 of those degraded
# answers; lower-better by the _ms rule), and prewarm_tiles_per_sec
# (controller sweep throughput draining an advisor plan; higher-better
# by the per_sec rule).
# Schema 14 adds the dispatch-pipeline flight recorder (bench.py
# bench_flight): flight_overhead_ratio (steady-state serve latency with
# the recorder armed over the recorder-off control; lower-better by the
# overhead rule — ~1.0 means instrumentation is invisible to the hot
# path), flight_device_busy_frac (fraction of the engine window covered
# by dispatch spans; higher-better — more overlap means fewer host
# bubbles), and flight_host_gap_frac (its complement; lower-better by
# the host_gap rule).
# Readers accept every version: the key set only grows, and
# `load` stamps schema-less legacy lines as 1, so a committed
# schema-1..13 history keeps gating new schema-14 appends.
SCHEMA = 14
_SPARK = "▁▂▃▄▅▆▇█"


def history_path(path=None) -> Path:
    """Resolve the history file: explicit arg > SBR_OBS_HISTORY env >
    ``benchmarks/bench_history.jsonl`` (the committed perf trajectory)."""
    if path:
        return Path(path)
    env = os.environ.get("SBR_OBS_HISTORY", "").strip()
    return Path(env) if env else Path("benchmarks/bench_history.jsonl")


def append(metrics: dict, label: str = "bench", platform: Optional[str] = None,
           path=None, meta: Optional[dict] = None) -> Path:
    """Append one history record (single buffered write — concurrent
    appenders interleave whole lines on POSIX). Non-finite and non-numeric
    metric values are dropped: the history carries only gateable numbers."""
    clean = {}
    for k, v in (metrics or {}).items():
        if isinstance(v, bool):
            v = int(v)
        if isinstance(v, (int, float)) and math.isfinite(v):
            clean[str(k)] = v
    rec = {
        "schema": SCHEMA,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "label": label,
        "platform": platform,
        "metrics": clean,
    }
    if meta:
        rec["meta"] = meta
    p = history_path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    return p


def load(path=None) -> list:
    """Parse a history file into record dicts, in file order; unparseable
    or schema-less lines are skipped (an append-only log must tolerate a
    torn tail write)."""
    p = history_path(path)
    records = []
    try:
        lines = p.read_text().splitlines()
    except OSError:
        return records
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and isinstance(rec.get("metrics"), dict):
            # Schema-less lines predate versioning (= schema 1); schemas
            # 2-8 are pure supersets, so every known version loads
            # uniformly and older lines keep gating newer appends.
            rec.setdefault("schema", 1)
            records.append(rec)
    return records


def bench_metrics(result: dict) -> dict:
    """Headline metrics from one bench-JSON result dict (the ``extra``
    layout of bench.py / benchmarks/*.py): primary metric under its own
    name, throughput/duration extras, and the obs compile/execute split."""
    out = {}
    value = result.get("value")
    if isinstance(value, (int, float)):
        out[str(result.get("metric") or "value")] = value
    extra = result.get("extra") or {}
    for key in (
        "agent_steps_per_sec",
        "grid_first_call_s",
        "grid_dispatch_s",
        "grid_pipelined_s",
        "agents_steady_s",
        "agents_prep_s",
        # schema 2: per-workload allocator peaks (absent on CPU backends
        # without memory_stats — the gate simply has no memory series there)
        "grid_mem_peak_bytes",
        "agents_mem_peak_bytes",
        # schema 3: the serving workload (bench.py bench_serve / loadgen):
        # latency quantiles are lower-better (_ms polarity), hit rate higher
        "serve_p50_ms",
        "serve_p99_ms",
        "serve_cache_hit_rate",
        # schema 4: the tiled-sweep workload (bench.py bench_sweep): cold
        # compute throughput, warm cross-run-cache re-sweep throughput, and
        # the warm hit rate (all higher-better by polarity)
        "sweep_cold_cells_per_sec",
        "sweep_warm_cells_per_sec",
        "sweep_warm_hit_rate",
        # schema 5: the adaptive-numerics split (bench.py bench_grid):
        # speedup of the default adaptive program over the bit-exact fixed
        # control (higher-better) and mean effective root-find iterations
        # per cell (lower-better by the _iters polarity rule)
        "grid_adaptive_speedup",
        "grid_mean_effective_iters",
        # schema 6: the mega-scale agents generation split (bench.py
        # bench_agents on graphgen): build duration lower-better by the _s
        # rule, generation throughput and device-vs-host speedup higher
        "agents_graph_build_s",
        "agents_graph_gen_edges_per_sec",
        "agents_graph_gen_speedup",
        # schema 7: the serving-fleet workload (loadgen --fleet / bench.py
        # bench_fleet): client p99 through the router, failover count, and
        # the admission shed rate (all lower-better by polarity)
        "fleet_p99_ms",
        "fleet_failover_count",
        "fleet_shed_rate",
        # schema 8: the differentiable-equilibria workload (bench.py
        # bench_grad): sensitivity-surface gradient throughput and
        # calibration step rate (both higher-better by the per_sec rule)
        "grads_per_sec",
        "calib_steps_per_sec",
        # schema 9: the composable-scenario workload (bench.py
        # bench_scenario): composed-over-legacy grid overhead ratio
        # (lower-better by the overhead rule) and multi-bank contagion
        # throughput (higher-better by the per_sec rule)
        "scenario_overhead_ratio",
        "scenario_multibank_cells_per_sec",
        # schema 10: the information-model workload (bench.py
        # bench_infomodels): fused belief-update throughput and population
        # what-if query rate (both higher-better by the per_sec rule)
        "infomodel_belief_updates_per_sec",
        "infomodel_population_queries_per_sec",
        # schema 11: the numerics-audit workload (bench.py bench_audit):
        # canary-battery probe throughput (higher-better by the per_sec
        # rule) and serve-loop audit-on/off overhead ratio (lower-better
        # by the overhead rule)
        "audit_probes_per_sec",
        "audit_overhead_ratio",
        # schema 12: the workload-demand observatory (bench.py
        # bench_demand): streaming sketch/histogram update throughput
        # (higher-better by the per_sec rule) and the router-side fleet
        # merge cost (lower-better by the _ms rule)
        "demand_updates_per_sec",
        "demand_merge_ms",
        # schema 13: the self-healing prefetch workload (bench.py
        # bench_prewarm): outage warm hit rate from prefetched tiles
        # (higher-better by the hit_rate rule), degraded-answer p99
        # (lower-better by the _ms rule), and controller sweep throughput
        # (higher-better by the per_sec rule)
        "prewarm_warm_hit_rate",
        "prewarm_outage_p99_ms",
        "prewarm_tiles_per_sec",
        # schema 14: the dispatch-pipeline flight recorder (bench.py
        # bench_flight): recorder-on over recorder-off serve latency
        # (lower-better by the overhead rule), device-busy fraction of
        # the engine window (higher-better — default polarity), and the
        # host-gap complement (lower-better by the host_gap rule)
        "flight_overhead_ratio",
        "flight_device_busy_frac",
        "flight_host_gap_frac",
    ):
        v = extra.get(key)
        if isinstance(v, (int, float)):
            # A zero byte-peak means "no allocator stats", not "used zero
            # bytes" — recording it would arm the zero-baseline regression
            # rule on noise. Durations/throughputs keep their raw value.
            if key.endswith("_bytes") and v <= 0:
                continue
            out[key] = v
    obs_blk = extra.get("obs") or {}
    for src, dst in (
        ("compile_s", "obs_compile_s"),
        ("execute_s", "obs_execute_s"),
        ("xla_backend_compile_s", "xla_backend_compile_s"),
        # schema 2: the run's overall peak (live-buffer based on CPU, so
        # memory regressions gate even without allocator stats)
        ("memory_peak_bytes", "mem_peak_bytes"),
    ):
        v = obs_blk.get(src)
        if isinstance(v, (int, float)):
            if dst.endswith("_bytes") and v <= 0:
                continue  # zero byte-peak = no data, not a clean baseline
            out[dst] = v
    return out


# ---------------------------------------------------------------------------
# Trend analysis
# ---------------------------------------------------------------------------


def polarity(metric: str) -> int:
    """+1 when higher is better (throughput, cache hit rates, speedups), -1
    when lower is better (durations, latencies, byte counts, divergence,
    effective-iteration, failover/shed counts, overhead ratios)."""
    m = metric.lower()
    if (
        m.endswith("_per_sec")
        or "per_sec" in m
        or "throughput" in m
        or "hit_rate" in m
        or "speedup" in m
    ):
        return 1
    if (
        m.endswith("_s")
        or m.endswith("_ms")
        or m.endswith("_bytes")
        or m.endswith("_iters")
        or m.endswith("_count")
        or "latency" in m
        or "divergent" in m
        or "retrace" in m
        or "shed" in m
        or "failover" in m
        # schema 9: a composed pipeline's cost over its legacy control —
        # growing overhead is a regression even though it's a ratio
        or "overhead" in m
        # schema 14: the host-side bubble fraction of the engine window —
        # a rising gap means the device is starving behind the host
        or "host_gap" in m
    ):
        return -1
    return 1


def _median(vals: list) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _same_platform(records: list, platform) -> list:
    """Records comparable to ``platform``: exact matches, plus records
    that never recorded one (legacy lines gate against everything)."""
    return [r for r in records if r.get("platform") in (platform, None)]


def recent_median(metric: str, path=None, platform=None, window: int = 8):
    """Median of the most recent ``window`` values of one metric in the
    history (optionally restricted to ``platform``), or None when the
    metric has never been recorded — the deterministic seed the elastic
    scheduler's cost model reads (`resilience.elastic`). jax-free, like
    everything in this module."""
    records = load(path)
    if platform is not None:
        records = _same_platform(records, platform)
    vals = [
        r["metrics"][metric]
        for r in records
        if isinstance(r.get("metrics", {}).get(metric), (int, float))
        and math.isfinite(r["metrics"][metric])
    ]
    if not vals:
        return None
    return _median(vals[-window:])


def check(records: list, tolerance: float = 0.15, min_points: int = 3,
          window: int = 5, metrics_filter=None) -> tuple:
    """Regression verdicts for the latest record vs a rolling-median
    baseline of up to ``window`` prior same-platform records.

    Returns ``(verdicts, status)`` where status is "ok", "regression", or
    "short" (no metric reached ``min_points`` records — the gate has
    nothing trustworthy to compare). Per-metric verdicts carry latest /
    baseline / signed relative change / direction / status.
    """
    if not records:
        return {}, "short"
    latest = records[-1]
    prior = _same_platform(records[:-1], latest.get("platform"))
    verdicts = {}
    gateable = 0
    for metric, value in sorted((latest.get("metrics") or {}).items()):
        if metrics_filter and metric not in metrics_filter:
            continue
        hist = [
            r["metrics"][metric]
            for r in prior
            if isinstance(r["metrics"].get(metric), (int, float))
        ]
        n = len(hist) + 1
        if n < min_points:
            verdicts[metric] = {"latest": value, "n": n, "status": "short"}
            continue
        gateable += 1
        base = _median(hist[-window:])
        pol = polarity(metric)
        direction = "higher_better" if pol > 0 else "lower_better"
        if base == 0:
            # Relative change is undefined; for lower-better counts (e.g.
            # health_divergent) any increase from a clean baseline regresses.
            change = None
            regressed = pol < 0 and value > 0
        else:
            change = (value - base) / abs(base)
            worsening = -change if pol > 0 else change
            regressed = worsening > tolerance
        verdicts[metric] = {
            "latest": value,
            "baseline": base,
            "n": n,
            "change": None if change is None else round(change, 4),
            "direction": direction,
            "status": "regression" if regressed else "ok",
        }
    if gateable == 0:
        return verdicts, "short"
    status = (
        "regression"
        if any(v["status"] == "regression" for v in verdicts.values())
        else "ok"
    )
    return verdicts, status


def sparkline(values: list, width: int = 24) -> str:
    """Unicode sparkline of a series, downsampled to ``width`` points."""
    vals = [v for v in values if isinstance(v, (int, float)) and math.isfinite(v)]
    if not vals:
        return ""
    if len(vals) > width:
        step = len(vals) / width
        vals = [vals[min(int(i * step), len(vals) - 1)] for i in range(width)]
    lo, hi = min(vals), max(vals)
    if hi == lo:
        return _SPARK[3] * len(vals)
    return "".join(_SPARK[int((v - lo) / (hi - lo) * (len(_SPARK) - 1))] for v in vals)


def _fmt_val(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render_trend(records: list, window: int = 5, metrics_filter=None) -> str:
    """Per-metric timeline table, grouped by platform: count, latest value,
    rolling-median baseline of the prior window, signed change, sparkline."""
    if not records:
        return "no history records"
    from sbr_tpu.obs.report import _table  # shared table renderer (jax-free)

    out = [
        f"history  {len(records)} record(s)   "
        f"{records[0].get('ts', '?')} .. {records[-1].get('ts', '?')}"
    ]
    platforms = sorted({r.get("platform") or "-" for r in records})
    for platform in platforms:
        recs = [r for r in records if (r.get("platform") or "-") == platform]
        metric_names = sorted({m for r in recs for m in (r.get("metrics") or {})})
        rows = []
        for metric in metric_names:
            if metrics_filter and metric not in metrics_filter:
                continue
            series = [
                r["metrics"][metric]
                for r in recs
                if isinstance(r["metrics"].get(metric), (int, float))
            ]
            if not series:
                continue
            base = _median(series[:-1][-window:]) if len(series) > 1 else None
            change = (
                f"{100 * (series[-1] - base) / abs(base):+.1f}%"
                if base not in (None, 0)
                else "-"
            )
            arrow = "↑" if polarity(metric) > 0 else "↓"
            rows.append(
                [
                    metric,
                    arrow,
                    len(series),
                    _fmt_val(series[-1]),
                    _fmt_val(base),
                    change,
                    sparkline(series),
                ]
            )
        if rows:
            out += ["", f"PLATFORM {platform}"]
            out.append(
                _table(
                    ["metric", "good", "n", "latest", "baseline", "change", "trend"],
                    rows,
                )
            )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# CLI (dispatched from `python -m sbr_tpu.obs.report trend ...`)
# ---------------------------------------------------------------------------


def main_trend(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sbr_tpu.obs.report trend",
        description="Render the perf history; with --check, gate on regressions "
        "(exit 1 regression, 3 missing/short history)",
    )
    parser.add_argument(
        "history", nargs="?", default=None,
        help="history JSONL (default: $SBR_OBS_HISTORY or benchmarks/bench_history.jsonl)",
    )
    parser.add_argument("--check", action="store_true",
                        help="gate: exit 1 on regression beyond tolerance")
    parser.add_argument("--tolerance", type=float, default=0.15, metavar="FRAC",
                        help="allowed relative worsening vs baseline (default 0.15)")
    parser.add_argument("--window", type=int, default=5, metavar="N",
                        help="rolling-median baseline window (default 5)")
    parser.add_argument("--min-points", type=int, default=3, metavar="N",
                        help="records required before a metric gates (default 3)")
    parser.add_argument("--metric", action="append", default=None, metavar="NAME",
                        help="restrict to metric NAME (repeatable)")
    parser.add_argument("--platform", default=None,
                        help="restrict to records from one platform")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)

    path = history_path(args.history)
    records = load(path)
    if args.platform:
        records = _same_platform(records, args.platform)
    if not records:
        # Missing/empty history only FAILS the gate (--check exit 3); a
        # render-only invocation on a fresh checkout is not an error.
        code = 3 if args.check else 0
        if args.json:
            print(json.dumps({"history": str(path), "n_records": 0, "status": "short",
                              "verdicts": {}, "exit": code}))
        else:
            print(f"no perf history at {path} — nothing to trend or gate",
                  file=sys.stderr)
        return code

    verdicts, status = check(
        records,
        tolerance=args.tolerance,
        min_points=args.min_points,
        window=args.window,
        metrics_filter=set(args.metric) if args.metric else None,
    )
    code = {"ok": 0, "regression": 1, "short": 3}[status] if args.check else 0
    if args.json:
        print(json.dumps({
            "history": str(path),
            "n_records": len(records),
            "platform": records[-1].get("platform"),
            "tolerance": args.tolerance,
            "window": args.window,
            "status": status,
            "verdicts": verdicts,
            "exit": code,
        }))
        return code

    print(render_trend(records, window=args.window,
                       metrics_filter=set(args.metric) if args.metric else None))
    if args.check:
        print()
        if status == "short":
            print(f"GATE: history too short (<{args.min_points} comparable records) "
                  "— not gateable (exit 3)")
        else:
            bad = [m for m, v in verdicts.items() if v["status"] == "regression"]
            for m in bad:
                v = verdicts[m]
                print(
                    f"REGRESSION  {m}: {_fmt_val(v['latest'])} vs baseline "
                    f"{_fmt_val(v['baseline'])} ({100 * v['change']:+.1f}%, "
                    f"{v['direction']}, tolerance {100 * args.tolerance:.0f}%)"
                )
            if not bad:
                print(f"GATE: ok — no metric regressed beyond "
                      f"{100 * args.tolerance:.0f}% of its rolling-median baseline")
    return code
